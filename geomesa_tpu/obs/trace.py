"""Hierarchical trace spans with ContextVar propagation (the obs core).

The per-query timeline the reference never had: every stage of
``QueryPlanner.runQuery`` (plan → range decomposition → device dispatch →
refine → reduce → serialize) opens a :class:`Span`; spans nest through a
``contextvars.ContextVar``, so propagation is correct across the threaded
web server's request threads and the watchdog's scan worker threads
(``utils.timeouts.run_with_timeout`` copies the context into its worker)
without any explicit plumbing.

Zero-overhead contract: with tracing disabled, :func:`span` returns a
shared no-op context manager after one module-global check and one
ContextVar read — no allocation, no clock read, and (critically) no jax
import anywhere in this module, so ``GEOMESA_TPU_NO_JAX=1`` keeps working.
The bound is asserted by ``tests/test_obs.py``.

Enable globally with :func:`enable` (or ``GEOMESA_TPU_TRACE=<path>`` in the
environment — bench.py's ``--trace`` sets it), or per-call-tree with
:func:`collect` (what ``DataStore.explain(..., analyze=True)`` uses).
Completed root spans land in a bounded in-memory buffer; exporters
(:mod:`geomesa_tpu.obs.export`) turn them into Chrome/Perfetto trace JSON.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar

__all__ = [
    "Span", "StageTimeline", "span", "collect", "current", "annotate",
    "enable", "disable", "enabled", "event", "recent", "drain", "NOOP",
    "TRACE_HEADER", "TRACE_RETURN_HEADER", "TraceContext", "inject",
    "extract", "propagated", "remote_owned", "serialize_subtree",
    "deserialize_subtree", "graft_serialized", "on_root_complete",
    "remove_root_listener", "unsampled_join",
]

# -- cross-process trace context (docs/observability.md § Distributed
# tracing). The request header carries ``trace_id;parent_span_id;flags``
# (flags bit 0 = sampled, W3C-traceparent style); the response header
# carries back a compact serialized span subtree the client grafts under
# its RPC span, so one federated query reads as ONE stitched tree.
TRACE_HEADER = "X-Geomesa-Trace"
TRACE_RETURN_HEADER = "X-Geomesa-Trace-Return"

_enabled = False  # module-global fast flag (the one check on the no-op path)
_forced: ContextVar[bool] = ContextVar("geomesa_obs_forced", default=False)
_current: ContextVar["Span | None"] = ContextVar("geomesa_obs_span", default=None)
# True inside a server-side `propagated` tree: the REMOTE caller owns this
# trace (the flight recorder must not park anomaly dumps on it — the
# local propagated root completing is not the stitched tree completing)
_remote_owned: ContextVar[bool] = ContextVar("geomesa_obs_remote", default=False)
# True inside a request joined from an UNSAMPLED incoming context:
# downstream inject() must carry flags=0 so the next hop does not
# force-record either — the flag is honored END TO END, not just here
_unsampled: ContextVar[bool] = ContextVar("geomesa_obs_unsampled", default=False)

_buffer_lock = threading.Lock()
_MAX_TRACES = 512  # completed root spans retained (ring buffer)
_traces: deque = deque(maxlen=_MAX_TRACES)

# span/trace ids: a per-process random salt + cheap counter — unique within
# and across processes without paying uuid4 per span
_salt = os.urandom(4).hex()
_ids = itertools.count(1)

# completed-root listeners: fn(root_span), registered by the flight
# recorder so anomaly dumps fire only once the whole tree is closed
_root_listeners: list = []


class Span:
    """One timed stage. Context manager; children attach automatically via
    the ContextVar, so concurrent requests build disjoint trees."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs", "children",
        "events", "t0_ns", "t1_ns", "thread_id", "_token",
    )

    def __init__(self, name: str, attrs: dict, parent: "Span | None"):
        self.name = name
        self.attrs = attrs
        self.children: list[Span] = []
        # point-in-time markers inside this span's window — (name, t_ns,
        # attrs) — the federation layer's member-error/degradation record
        self.events: list[tuple] = []
        sid = next(_ids)
        self.span_id = f"{_salt}-{sid:x}"
        if parent is None:
            self.trace_id = f"{_salt}-t{sid:x}"
            self.parent_id = ""
        else:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        self.t0_ns = 0
        self.t1_ns = 0
        self.thread_id = threading.get_ident()
        self._token = None

    # -- timing ---------------------------------------------------------------
    @property
    def duration_ms(self) -> float:
        end = self.t1_ns if self.t1_ns else time.perf_counter_ns()
        return (end - self.t0_ns) / 1e6

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> "Span":
        """Record a point-in-time marker on this span (list.append is
        atomic under the GIL; exporters snapshot via list())."""
        self.events.append((name, time.perf_counter_ns(), attrs))
        return self

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.t1_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        parent = None
        if self._token is not None:
            prev = self._token.old_value  # Token.MISSING when var was unset
            _current.reset(self._token)
            self._token = None
            if isinstance(prev, Span):
                parent = prev
        if parent is not None:
            # list.append is atomic under the GIL; an abandoned (timed-out)
            # scan worker may attach late — exporters snapshot via list()
            parent.children.append(self)
        else:
            with _buffer_lock:
                _traces.append(self)
            # completed-root listeners (the flight recorder's anomaly-dump
            # trigger): called OUTSIDE the buffer lock, errors swallowed —
            # a broken listener must never fail the traced call itself
            for fn in list(_root_listeners):
                try:
                    fn(self)
                except Exception:  # noqa: BLE001 — observer, not participant
                    pass

    # -- introspection --------------------------------------------------------
    def walk(self):
        """This span and every descendant, depth-first."""
        yield self
        for c in list(self.children):
            yield from c.walk()

    def find(self, name: str) -> "list[Span]":
        return [s for s in self.walk() if s.name == name]

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (
            f"Span({self.name!r}, {self.duration_ms:.3f} ms, "
            f"children={len(self.children)})"
        )


class _NoopSpan:
    """The disabled path: one shared instance, every method a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    # mimic the Span read surface so call sites never branch on type
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = ""
    attrs: dict = {}
    children: list = []
    events: list = []
    duration_ms = 0.0

    def walk(self):
        return iter(())

    def find(self, name):
        return []


NOOP = _NoopSpan()


def active() -> bool:
    """True when spans are being recorded on THIS context (global enable or
    an enclosing :func:`collect`)."""
    return _enabled or _forced.get()


def enabled() -> bool:
    return _enabled


def enable(jax_telemetry: bool = True) -> None:
    """Turn tracing on process-wide. ``jax_telemetry`` also installs the
    jax.monitoring compile listeners — guarded so a ``GEOMESA_TPU_NO_JAX=1``
    process never imports jax from here."""
    global _enabled
    _enabled = True
    if jax_telemetry:
        from geomesa_tpu.obs import jaxmon

        jaxmon.install()


def disable() -> None:
    global _enabled
    _enabled = False


def span(name: str, **attrs) -> "Span | _NoopSpan":
    """Open a child span of the current context (a root when none).

    Usage: ``with obs.span("plan", index="z3"): ...`` — returns the shared
    no-op singleton when tracing is off.
    """
    if not _enabled and not _forced.get():
        return NOOP
    return Span(name, attrs, _current.get())


def current() -> "Span | None":
    """The innermost live span on this context, or None."""
    return _current.get()


def annotate(**attrs) -> None:
    """Attach attributes to the innermost live span (no-op when untraced)."""
    sp = _current.get()
    if sp is not None:
        sp.attrs.update(attrs)


def event(name: str, **attrs) -> None:
    """Record a point-in-time marker on the innermost live span (no-op
    when untraced) — e.g. a federation member error inside a query span."""
    sp = _current.get()
    if sp is not None:
        sp.event(name, **attrs)


@contextmanager
def collect(name: str = "trace", **attrs):
    """Force-trace one call tree regardless of the global flag and yield its
    root span (inspect ``root.children`` after the block). This is the
    ``EXPLAIN ANALYZE`` mechanism: per-query opt-in with zero ambient cost."""
    tok = _forced.set(True)
    root = Span(name, attrs, _current.get())
    try:
        with root:
            yield root
    finally:
        _forced.reset(tok)


def on_root_complete(fn) -> None:
    """Register ``fn(root_span)`` to run whenever a root span completes
    (after it lands in the buffer; called outside every obs lock)."""
    _root_listeners.append(fn)


def remove_root_listener(fn) -> None:
    try:
        _root_listeners.remove(fn)
    except ValueError:
        pass


# -- cross-process propagation (the federation trace contract) ---------------

class TraceContext:
    """Parsed ``X-Geomesa-Trace`` header: the caller's trace identity plus
    the sampled flag a remote member must honor."""

    __slots__ = ("trace_id", "parent_span_id", "sampled")

    def __init__(self, trace_id: str, parent_span_id: str, sampled: bool):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.sampled = sampled

    def header_value(self) -> str:
        return f"{self.trace_id};{self.parent_span_id};{int(self.sampled)}"

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (f"TraceContext({self.trace_id!r}, {self.parent_span_id!r}, "
                f"sampled={self.sampled})")


def inject() -> str | None:
    """Header value for the innermost live span (None when untraced) —
    what the HTTP choke point stamps on every outbound RPC. A locally
    originated trace is sampled (we ARE recording it); a tree joined from
    an unsampled upstream context stays unsampled downstream."""
    sp = _current.get()
    if sp is None:
        return None
    flags = 0 if _unsampled.get() else 1
    return f"{sp.trace_id};{sp.span_id};{flags}"


@contextmanager
def unsampled_join():
    """Mark this call tree as joined from an UNSAMPLED incoming context:
    local spans may still record (ids join the caller's trace), but
    outbound :func:`inject` carries flags=0 so downstream members are not
    force-recorded — honoring the caller's sampling decision end to end
    (the web layer wraps unsampled-context requests in this)."""
    tok = _unsampled.set(True)
    try:
        yield
    finally:
        _unsampled.reset(tok)


def extract(header: str | None) -> TraceContext | None:
    """Parse an incoming ``X-Geomesa-Trace`` header. Malformed values
    yield None (propagation is best-effort, never a request error)."""
    if not header:
        return None
    parts = header.split(";")
    if len(parts) != 3 or not parts[0] or not parts[1]:
        return None
    trace_id, parent_id, flags = parts
    if any(len(p) > 128 for p in parts):
        return None  # defensive: a hostile header must not bloat every span
    return TraceContext(trace_id, parent_id, flags.strip() == "1")


@contextmanager
def propagated(name: str, ctx: TraceContext, **attrs):
    """Server-side trace join: force-record one call tree as a child of
    the remote caller's span (the ``collect`` mechanism with the caller's
    ids), honoring the sampled flag — this is how a remote member's spans
    end up inside the federated caller's stitched tree."""
    tok = _forced.set(True)
    rtok = _remote_owned.set(True)
    root = Span(name, attrs, _current.get())
    root.trace_id = ctx.trace_id
    root.parent_id = ctx.parent_span_id
    try:
        with root:
            yield root
    finally:
        _remote_owned.reset(rtok)
        _forced.reset(tok)


def remote_owned() -> bool:
    """True when this context's trace is owned by a remote caller (we are
    inside a server-side ``propagated`` tree)."""
    return _remote_owned.get()


def _prim(v):
    if isinstance(v, (int, float, bool, str, type(None))):
        return v
    s = str(v)
    return s if len(s) <= 200 else s[:197] + "..."


def _span_doc(s: Span, base_ns: int, depth: int) -> dict:
    d = {
        "n": s.name,
        "i": s.span_id,
        "th": s.thread_id,
        "o": s.t0_ns - base_ns,
        "d": max((s.t1_ns or s.t0_ns) - s.t0_ns, 0),
        "a": {k: _prim(v) for k, v in s.attrs.items()},
    }
    evs = [[n, t - base_ns, {k: _prim(v) for k, v in a.items()}]
           for n, t, a in list(s.events)]
    if evs:
        d["e"] = evs
    if depth > 0 and s.children:
        d["c"] = [_span_doc(c, base_ns, depth - 1) for c in list(s.children)]
    elif s.children:
        d["pruned"] = len(s.children)
    return d


def serialize_subtree(root: Span, max_bytes: int = 48_000) -> str:
    """One span tree as a compact, header-safe string (JSON → zlib →
    base64). Timestamps ship RELATIVE to the root's start, so the clock
    domains of two hosts never need to agree. Oversized trees prune the
    deepest levels first until the encoding fits ``max_bytes``."""
    for depth in (64, 6, 3, 1, 0):
        doc = _span_doc(root, root.t0_ns, depth)
        enc = base64.b64encode(
            zlib.compress(json.dumps(doc, separators=(",", ":")).encode())
        ).decode("ascii")
        if len(enc) <= max_bytes:
            return enc
    return enc  # depth 0: a single span always fits in practice


def _build_span(doc: dict, trace_id: str, base_ns: int) -> Span:
    sp = Span(str(doc.get("n", "?")), dict(doc.get("a") or {}), None)
    sp.trace_id = trace_id
    sp.span_id = str(doc.get("i", sp.span_id))
    sp.thread_id = int(doc.get("th", 0))
    sp.t0_ns = base_ns + int(doc.get("o", 0))
    sp.t1_ns = sp.t0_ns + int(doc.get("d", 0))
    for n, t, a in doc.get("e", ()):
        sp.events.append((str(n), base_ns + int(t), dict(a)))
    if doc.get("pruned"):
        sp.attrs["children_pruned"] = int(doc["pruned"])
    for c in doc.get("c", ()):
        child = _build_span(c, trace_id, base_ns)
        child.parent_id = sp.span_id
        sp.children.append(child)
    return sp


# inflated-payload ceiling for remote-supplied subtrees: a 64 KB header
# (http.client's line limit) crafted as a zlib bomb must not expand into
# hundreds of MB on the client — decompression stops at this many bytes
_MAX_INFLATED_BYTES = 4 * 1024 * 1024


def _decode_subtree_doc(encoded: str) -> dict:
    d = zlib.decompressobj()
    raw = d.decompress(base64.b64decode(encoded), _MAX_INFLATED_BYTES)
    if d.unconsumed_tail:
        raise ValueError(
            f"serialized span subtree inflates past {_MAX_INFLATED_BYTES} B")
    return json.loads(raw.decode())


def deserialize_subtree(encoded: str, trace_id: str = "",
                        base_ns: int = 0) -> Span:
    """Inverse of :func:`serialize_subtree`: a real :class:`Span` tree
    (walk/find/exporters all work), re-anchored at ``base_ns``."""
    return _build_span(_decode_subtree_doc(encoded), trace_id, base_ns)


def graft_serialized(parent: Span, encoded: str) -> Span | None:
    """Graft a remote member's serialized subtree under the local RPC
    span: the remote root becomes a child of ``parent``, its ids rebased
    onto the parent's trace and its clock re-anchored inside the RPC
    window (centered — the residual on either side reads as network
    time). Returns the grafted root, or None on a malformed payload."""
    try:
        doc = _decode_subtree_doc(encoded)
    except Exception:  # noqa: BLE001 — a torn header must not fail the call
        return None
    elapsed = (parent.t1_ns or time.perf_counter_ns()) - parent.t0_ns
    remote_dur = int(doc.get("d", 0))
    base = parent.t0_ns + max((elapsed - remote_dur) // 2, 0)
    root = _build_span(doc, parent.trace_id, base)
    root.parent_id = parent.span_id
    parent.children.append(root)
    return root


def recent() -> list:
    """Completed root spans, oldest first (non-destructive)."""
    with _buffer_lock:
        return list(_traces)


def find_trace(trace_id: str) -> "Span | None":
    """The completed root span with this trace_id (newest wins), or None.
    Exemplar resolution: the query lens's tail buckets retain trace ids
    (obs/lens.py); this turns one back into its stitched span tree while
    it is still inside the completed-roots ring."""
    if not trace_id:
        return None
    with _buffer_lock:
        for root in reversed(_traces):
            if root.trace_id == trace_id:
                return root
    return None


def span_doc(root: "Span", max_depth: int = 64) -> dict:
    """One span tree as plain JSON — the web layer's exemplar-resolution
    payload (``GET /api/obs/lens?trace=``). Same compact keys as the
    federation wire doc (n/i/o/d/a/e/c, offsets relative to the root's
    start) plus the absolute anchor so clients can line trees up."""
    d = _span_doc(root, root.t0_ns, max_depth)
    d["trace_id"] = root.trace_id
    d["t0_ns"] = root.t0_ns
    return d


def drain() -> list:
    """Completed root spans, clearing the buffer (exporter consumption)."""
    with _buffer_lock:
        out = list(_traces)
        _traces.clear()
    return out


class StageTimeline:
    """A root span flattened to the stage decomposition the acceptance
    contract names: direct children as (stage, ms) pairs plus an ``other``
    residual. Child durations are CLAMPED to the root's own window —
    a still-open child (an abandoned, timed-out scan worker whose span
    never closed) or one attached late cannot push coverage past wall —
    so for the sequential query pipeline stage durations sum to wall time
    by construction (``other`` absorbs untraced gaps)."""

    def __init__(self, root: Span):
        self.root = root
        self.wall_ms = root.duration_ms
        root_end = root.t1_ns if root.t1_ns else time.perf_counter_ns()
        stages = []
        for c in list(root.children):
            child_end = c.t1_ns if c.t1_ns else root_end  # still open
            lo = max(c.t0_ns, root.t0_ns)
            hi = min(child_end, root_end)
            stages.append((c.name, max(hi - lo, 0) / 1e6))
        covered = sum(ms for _, ms in stages)
        other = self.wall_ms - covered
        if other > 1e-6:
            stages.append(("other", other))
        self.stages = stages

    def stage_ms(self, name: str) -> float:
        return sum(ms for n, ms in self.stages if n == name)

    def render(self) -> str:
        lines = [
            f"Stage timeline ({self.wall_ms:.3f} ms wall, "
            f"trace {self.root.trace_id}):"
        ]
        for n, ms in self.stages:
            pct = 100.0 * ms / self.wall_ms if self.wall_ms else 0.0
            lines.append(f"  {n:<12s} {ms:10.3f} ms  {pct:5.1f}%")
        return "\n".join(lines)

    __str__ = render


# bench.py --trace / operator opt-in without code: enabling via environment
# here means child worker processes (bench driver mode) inherit tracing
if os.environ.get("GEOMESA_TPU_TRACE"):
    enable()
