"""Always-on query-audit flight recorder.

Role parity: the reference's query audit trail (``QueryAuditEndpoint`` /
``AuditWriter``) is an always-on operational record, not an opt-in
debugging tool. This module is that record for the federation era: a
lock-guarded bounded ring buffer holding one :class:`QueryAuditRecord`
per COMPLETED query — trace id, plan summary, per-member outcomes,
degraded flag, rows, latency — cheap enough to stay on in production
(the <2% bound on the cached-jit select path is asserted by
``tests/test_obs_federation.py`` and gated in ``scripts/lint.sh``).

Anomalies — a blown deadline, an open circuit breaker, a degraded
(partial) result, latency above the slow threshold — additionally
trigger a *flight dump*: one Perfetto-loadable JSON file containing the
triggering query's full span tree plus the recent ring contents, written
to ``dump_dir`` (``GEOMESA_TPU_FLIGHT_DIR``). Dumps are rate-limited
(``min_dump_interval_s``) so an anomaly storm costs one file, not one
per query. When tracing is active the dump waits for the triggering
trace's ROOT span to complete (via :func:`trace.on_root_complete`), so
the file holds the whole stitched federated tree, remote subtrees
included.

Surfaces: ``GET /api/obs/flight`` (:mod:`geomesa_tpu.web.app`) and
``geomesa-tpu obs flight`` (:mod:`geomesa_tpu.cli`).

Locking: one leaf lock guards the ring + pending-anomaly table (same
tier as the metrics registry locks — docs/concurrency.md). File I/O and
trace-tree serialization always run OUTSIDE it. No jax anywhere
(``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field

from geomesa_tpu.obs import trace as _trace

__all__ = [
    "FlightRecorder", "QueryAuditRecord", "get", "install", "record",
]

# operator knobs (read once at recorder construction)
FLIGHT_DIR_ENV = "GEOMESA_TPU_FLIGHT_DIR"
SLOW_MS_ENV = "GEOMESA_TPU_FLIGHT_SLOW_MS"

# anomaly kinds (QueryAuditRecord.anomalies entries)
A_DEADLINE = "deadline"
A_BREAKER = "breaker_open"
A_DEGRADED = "degraded"
A_SLOW = "slow"
# an admission-control shed (serving/admission.py): the request never
# reached the store — the record exists so "who is being shed and why"
# is answerable from the flight recorder alone
A_SHED = "shed"
# a correctness divergence (obs/audit.py): the live answer disagreed
# with the independent referee re-execution, or an invariant sweep
# found structural drift — the highest-severity anomaly the recorder
# carries (a wrong answer outranks a slow one)
A_DIVERGE = "diverge"
# a sustained latency regression (obs/lens.py): the sentinel found a
# plan signature's live window p50/p99 above factor x its rolling
# reference window or committed BENCH baseline
A_REGRESSION = "regression"
# a recompile storm (obs/jaxmon.py): the live J003 census crossed the
# per-window recompile threshold — some step is being re-traced on a
# hot path (shape churn, a missing pad bucket)
A_RECOMPILE = "recompile_storm"
# a live shard migration stalled (serving/elastic.py): a pre-cutover
# drain or catch-up replay blew its timeout and the migrator rolled the
# move back, or the post-cutover drain timed out and the source's copies
# were retained (unreachable but undropped) — either way an operator
# should look before retrying (docs/operations.md § Migration triage)
A_MIGRATION = "migration_stall"
# a standing-query backlog burn (obs/streamlens.py): the backlog sentinel
# found a topic's watermark freshness, scanner queue depth, or
# stream.delivery SLO burn rate sustained past threshold — deliveries are
# falling behind the stream (docs/operations.md § Standing-query health)
A_BACKLOG = "backlog"
# a poisoned streaming chunk (stream/pipeline.py _drop_failed): staging /
# scan / delivery raised and the chunk was dropped with its rows marked
# scanned — every active subscription of the topic silently missed those
# rows, which is why this is an anomaly and not just a counter
A_STREAM_ERROR = "stream_error"


@dataclass
class QueryAuditRecord:
    """One completed query, as the flight recorder remembers it."""

    ts: float  # unix seconds at completion
    op: str  # "query" | "select_many" | "stats_count" | ...
    type_name: str
    source: str  # "store" | "federation" | ...
    plan: str  # filter / plan summary text
    latency_ms: float
    rows: int
    trace_id: str = ""
    bytes_out: int = 0
    degraded: bool = False
    # tenant attribution (obs.usage): the calling identity the web layer
    # extracted (X-Geomesa-Tenant / auth principal), "" when the query
    # ran outside any tenant context (embedded use, tests)
    tenant: str = ""
    # the caller's visibility auths at execution time (None = unrestricted)
    auths: tuple | None = None
    # the executed plan's cost-table key (devmon.plan_signature) and the
    # model's pre-run p50 prediction — what replay reports key on
    plan_signature: str = ""
    predicted_ms: float | None = None
    # per-member outcomes for federated queries:
    # (member_index, "ok" | "error:<Type>", member_ms)
    members: list = field(default_factory=list)
    # stage -> ms latency breakdown (plan/scan/... where the caller has it)
    breakdown: dict = field(default_factory=dict)
    # devprof attribution for sampled queries (obs.devmon): compile /
    # dispatch / device_compute / h2d / d2h ms + transfer bytes; empty
    # when the query was not profiled
    device: dict = field(default_factory=dict)
    anomalies: tuple = ()


class FlightRecorder:
    """Bounded, lock-guarded ring of :class:`QueryAuditRecord` plus the
    anomaly-dump machinery. Thread-safe; one leaf lock, no blocking calls
    under it."""

    def __init__(self, capacity: int = 2048,
                 slow_ms: float | None = None,
                 dump_dir: str | None = None,
                 min_dump_interval_s: float = 30.0,
                 clock=time.time):
        if slow_ms is None:
            slow_ms = float(os.environ.get(SLOW_MS_ENV, "1000"))
        if dump_dir is None:
            dump_dir = os.environ.get(FLIGHT_DIR_ENV) or None
        self.slow_ms = slow_ms
        self.dump_dir = dump_dir
        self.min_dump_interval_s = min_dump_interval_s
        self._clock = clock
        self._lock = threading.Lock()  # leaf: ring + pending + dump clock
        self._ring: deque = deque(maxlen=capacity)
        # anomalies waiting for their trace's root span to complete (the
        # dump wants the WHOLE stitched tree); bounded so a listener that
        # never fires (root abandoned) cannot grow it forever
        self._pending: dict[str, QueryAuditRecord] = {}
        self._pending_cap = 64
        self._listener_installed = False
        self._last_dump_at = -float("inf")
        self._dump_seq = 0  # filename sequencing, counts attempts
        self.record_count = 0
        self.dump_count = 0  # SUCCESSFUL dumps only (the operator surface)
        self.last_dump_path: str | None = None

    # -- the hot path ---------------------------------------------------------
    def record(self, rec: QueryAuditRecord) -> QueryAuditRecord:
        """Append one completed-query record. Anomaly classification is
        cheap (flag checks); dump work is deferred. The ring stores plain
        tuples — :class:`QueryAuditRecord` materializes on READ
        (:meth:`records`), keeping the always-on write path to one time
        read, a few comparisons, and a locked deque append (the <2%
        bound gated in scripts/lint.sh)."""
        anomalies = self.record_values(
            rec.ts, rec.op, rec.type_name, rec.source, rec.plan,
            rec.latency_ms, rec.rows, rec.trace_id, rec.bytes_out,
            rec.degraded, rec.members, rec.breakdown, rec.anomalies,
            rec.device, rec.tenant, rec.auths, rec.plan_signature,
            rec.predicted_ms,
        )
        rec.anomalies = anomalies
        return rec

    def record_values(self, ts, op, type_name, source, plan, latency_ms,
                      rows, trace_id, bytes_out, degraded, members,
                      breakdown, anomalies, device=(), tenant="",
                      auths=None, plan_signature="",
                      predicted_ms=None) -> tuple:
        """Positional hot path (what :func:`record` at module level
        calls); returns the final anomaly tuple."""
        if degraded and A_DEGRADED not in anomalies:
            anomalies = anomalies + (A_DEGRADED,)
        if latency_ms > self.slow_ms and A_SLOW not in anomalies:
            anomalies = anomalies + (A_SLOW,)
        row = (ts, op, type_name, source, plan, latency_ms, rows, trace_id,
               bytes_out, degraded, members, breakdown, anomalies, device,
               tenant, auths, plan_signature, predicted_ms)
        dump_now = False
        install_listener = False
        # a trace owned by a REMOTE caller never parks: the local
        # (propagated) root completing is not the stitched tree
        # completing — the caller's recorder dumps on its side
        remote_owned = _trace.remote_owned()
        with self._lock:
            self._ring.append(row)
            self.record_count += 1
            if anomalies and self.dump_dir and not remote_owned:
                if trace_id and _trace.active():
                    # the triggering root span is still open (we are inside
                    # it): park the record; _on_root dumps when it closes.
                    # A full table evicts its OLDEST entry (a root that
                    # never completed) rather than dropping the new one.
                    if (trace_id not in self._pending
                            and len(self._pending) >= self._pending_cap):
                        self._pending.pop(next(iter(self._pending)))
                    self._pending[trace_id] = row
                    if not self._listener_installed:
                        self._listener_installed = True
                        install_listener = True
                else:
                    dump_now = True
        if install_listener:
            _trace.on_root_complete(self._on_root)
        if dump_now:
            self._dump(row, root=None)
        return anomalies

    @staticmethod
    def _materialize(row: tuple) -> QueryAuditRecord:
        (ts, op, type_name, source, plan, latency_ms, rows, trace_id,
         bytes_out, degraded, members, breakdown, anomalies, device,
         tenant, auths, plan_signature, predicted_ms) = row
        return QueryAuditRecord(
            ts=ts, op=op, type_name=type_name, source=source, plan=plan,
            latency_ms=latency_ms, rows=rows, trace_id=trace_id,
            bytes_out=bytes_out, degraded=degraded,
            members=list(members) if members else [],
            breakdown=dict(breakdown) if breakdown else {},
            device=dict(device) if device else {},
            anomalies=anomalies,
            tenant=tenant,
            auths=tuple(auths) if auths is not None else None,
            plan_signature=plan_signature,
            predicted_ms=predicted_ms,
        )

    # -- anomaly dumps --------------------------------------------------------
    def _on_root(self, root) -> None:
        if root.parent_id:
            # a PROPAGATED root (a remote caller's sampled request tree,
            # web/app.py): the caller owns the trace and dumps the full
            # stitched tree on its side — dumping each member request's
            # fragment here would fire once per RPC with a partial tree
            return
        with self._lock:
            row = self._pending.pop(root.trace_id, None)
        if row is not None:
            self._dump(row, root)

    def _dump(self, row: tuple, root) -> None:
        """Write one flight-dump file (throttled). Runs outside the ring
        lock: serialization + file I/O must never stall the hot path.
        ``dump_count``/``last_dump_path`` move only on a SUCCESSFUL
        write, and a failed write releases its throttle reservation — a
        full disk must not both report phantom dumps and suppress the
        next real one for a whole interval."""
        with self._lock:
            now = self._clock()
            prev_last = self._last_dump_at
            if now - prev_last < self.min_dump_interval_s:
                return
            self._last_dump_at = now  # reservation: one writer per window
            recent = list(self._ring)
            seq = self._dump_seq
            self._dump_seq += 1
        rec = self._materialize(row)
        if root is None and rec.trace_id:
            # tracing was on but the root closed before record() ran (or
            # closed without the listener): take it from the trace buffer
            for r in reversed(_trace.recent()):
                if r.trace_id == rec.trace_id:
                    root = r
                    break
        from geomesa_tpu.obs.export import chrome_trace_events

        events = chrome_trace_events([root] if root is not None else [])
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            # Perfetto ignores unknown top-level keys; operators (and the
            # CLI) read the flight section directly
            "flight": {
                "trigger": asdict(rec),
                "recent": [asdict(self._materialize(r))
                           for r in recent[-256:]],
            },
        }
        tag = rec.trace_id or f"seq{seq}"
        path = os.path.join(
            self.dump_dir, f"flight-{int(rec.ts * 1000)}-{tag}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
        except OSError:
            # a full/readonly disk must not fail the query path — and the
            # failed attempt must not hold the throttle window (unless a
            # concurrent successful dump re-reserved it meanwhile)
            with self._lock:
                if self._last_dump_at == now:
                    self._last_dump_at = prev_last
            return
        with self._lock:
            self.dump_count += 1
            self.last_dump_path = path

    def dump(self, path: str) -> int:
        """Operator-requested dump of the current ring (no anomaly
        needed); returns the record count written."""
        recent = self.records()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"flight": {"recent": [asdict(r) for r in recent]}}, fh)
        return len(recent)

    # -- read surface ---------------------------------------------------------
    def records(self) -> list:
        """Ring contents as :class:`QueryAuditRecord`, oldest first
        (non-destructive; materialized from the stored tuples)."""
        with self._lock:
            rows = list(self._ring)
        return [self._materialize(r) for r in rows]

    def snapshot(self, limit: int = 64, tenant: str | None = None,
                 type_name: str | None = None,
                 anomalies_only: bool = False) -> dict:
        """The ``/api/obs/flight`` payload: newest ``limit`` records plus
        recorder health. Optional server-side filters (``?tenant=`` /
        ``?type=`` / ``?anomalies=1``) apply BEFORE the limit, so "the
        last 64 anomalous records of tenant X" needs no client-side scan
        of the whole ring."""
        with self._lock:
            rows = list(self._ring)
            count, dumps, last = (self.record_count, self.dump_count,
                                  self.last_dump_path)
        if tenant is not None:
            rows = [r for r in rows if r[14] == tenant]
        if type_name is not None:
            rows = [r for r in rows if r[2] == type_name]
        if anomalies_only:
            rows = [r for r in rows if r[12]]
        rows = rows[-limit:]
        return {
            "records": [asdict(self._materialize(r)) for r in rows],
            "record_count": count,
            "dump_count": dumps,
            "last_dump": last,
            "capacity": self._ring.maxlen,
            "slow_ms": self.slow_ms,
            "dump_dir": self.dump_dir,
        }


# process-wide recorder: always on (recording is cheap; DUMPS only happen
# when a dump_dir is configured). Tests swap it with install().
_recorder = FlightRecorder()


def get() -> FlightRecorder:
    return _recorder


def install(rec: FlightRecorder) -> FlightRecorder:
    """Swap the process recorder (test isolation / reconfiguration);
    returns the previous one. The outgoing recorder's root-completion
    listener is deregistered (and re-registers on demand if the recorder
    is ever installed again) so repeated swaps never accumulate stale
    listeners or let a retired recorder keep writing dumps."""
    global _recorder
    prev, _recorder = _recorder, rec
    with prev._lock:
        had_listener = prev._listener_installed
        prev._listener_installed = False
        prev._pending.clear()
    if had_listener:
        _trace.remove_root_listener(prev._on_root)
    return prev


def record(op: str, type_name: str, *, source: str = "store",
           plan: str = "", latency_ms: float = 0.0, rows: int = 0,
           bytes_out: int = 0, degraded: bool = False, members=None,
           breakdown=None, anomalies: tuple = (), device=None,
           tenant: str = "", auths=None, plan_signature: str = "",
           predicted_ms=None) -> None:
    """Record one completed query on the process recorder (the store /
    federation call-site helper — trace id is taken from the live span).
    The always-on hot path: no dataclass is built here."""
    sp = _trace.current()
    _recorder.record_values(
        time.time(), op, type_name, source, plan, latency_ms, rows,
        sp.trace_id if sp is not None else "", bytes_out, degraded,
        members or (), breakdown or (), tuple(anomalies), device or (),
        tenant, auths, plan_signature, predicted_ms,
    )
