"""Deterministic workload replay — re-run captured production traffic
against a live store and diff the outcome against the recording
(docs/observability.md § Usage metering & workload replay).

A planner / cost-model / admission-control change is only trustworthy
under a REALISTIC query mix (PAPERS.md, *Large-Scale Geospatial
Processing on Multi-Core and Many-Core Processors*: batch-parallel
evaluation results hold under real workloads, not synthetic uniform
benches). This harness closes the loop: capture yesterday's traffic with
:mod:`geomesa_tpu.obs.workload`, apply the change, replay, and read the
recorded-vs-replayed report before deploying.

Modes:

- **closed-loop** (default): queries re-issue back-to-back at max speed,
  in the deterministic capture order (``(ts_arrival, seq)``) — the
  throughput / parity mode.
- **open-loop** (``speed=...``): queries re-issue at the RECORDED
  inter-arrival spacing divided by the speed factor (2.0 = twice as
  fast) — the latency-under-load mode, preserving the workload's burst
  structure.

Every replayed query runs under the recorded tenant's context
(:func:`geomesa_tpu.obs.usage.tenant_context`), so metering, flight
records, and federated RPC attribution behave exactly as they did in
production. Row-count parity per query is the correctness check: a
planner change may move latency, but a changed ANSWER fails the replay.

The report keys latency comparisons by plan signature (p50/p95/p99
recorded vs replayed) and serializes in the shape
``bench.py --regress`` loads as a baseline (a ``configs`` map of
``{"value", "unit", "parity"}``), so replay reports slot into the
existing perf-regression tooling. Surfaces: ``geomesa-tpu replay`` (CLI)
and :func:`run` here. No jax anywhere (``GEOMESA_TPU_NO_JAX=1`` safe —
the STORE does the device work).
"""

from __future__ import annotations

import json
import time

from geomesa_tpu.obs import usage as _usage
from geomesa_tpu.obs import workload as _workload

__all__ = ["load_events", "replay", "replay_bundle", "run", "write_report"]

# ops the harness knows how to re-issue (every captured shape today is a
# per-query audit event; batched paths audit per member query)
_REPLAYABLE_OPS = ("query",)


def load_events(path_or_dir: str, *, tenant: str | None = None,
                type_name: str | None = None, source: str | None = None,
                ops=_REPLAYABLE_OPS, limit: int | None = None) -> list[dict]:
    """Captured events in deterministic replay order, filtered. ``source``
    picks the capture tier to re-issue (``"store"`` for a single-store
    capture, ``"federation"`` for a frontend capture — replaying BOTH
    from one in-process capture would double-issue every federated
    query)."""
    events = _workload.read_events(path_or_dir)
    if ops:
        events = [e for e in events if e.get("op") in ops]
    if tenant is not None:
        events = [e for e in events if e.get("tenant") == tenant]
    if type_name is not None:
        events = [e for e in events if e.get("type") == type_name]
    if source is not None:
        events = [e for e in events if e.get("source") == source]
    if limit is not None:
        events = events[:limit]
    return events


def _query_of(event: dict):
    """Rebuild the re-issuable Query from one wide event."""
    from geomesa_tpu.planning.planner import Query

    filt = event.get("filter") or None
    if filt == "INCLUDE":
        filt = None
    hints = dict(event.get("hints") or {})
    if event.get("tenant"):
        hints["tenant"] = event["tenant"]
    auths = event.get("auths")
    return Query(filter=filt, hints=hints,
                 auths=list(auths) if auths is not None else None)


def replay(store, events, *, speed: float | None = None,
           remote: bool = False,
           _sleep=time.sleep, _clock=time.perf_counter) -> list[dict]:
    """Re-issue ``events`` against ``store``; returns one outcome dict per
    event: replayed latency/rows, row parity vs the recording, and the
    error type for a query that no longer executes (a dropped schema, an
    unparseable reconstructed filter — counted, never fatal: a replay
    must survive the store having moved on).

    ``speed=None`` → closed-loop (max speed). ``speed=s`` → open-loop at
    the recorded inter-arrival times divided by ``s``.

    ``remote=True`` (the ``--url`` path): the RemoteDataStore query
    surface forwards filter/limit/sort only — an event carrying other
    hints (density/stats/bin reshape the row count) or recorded auths
    (the client fails closed without the remote's trusted header) CANNOT
    round-trip faithfully, so it is SKIPPED and counted rather than
    replayed into a guaranteed false parity failure.

    Capture is SUSPENDED for the duration: replaying a directory the
    process is also capturing into would append every replayed query
    back onto the recording it is reading (and eventually rotate the
    original traffic off disk)."""
    prev_journal = _workload.install(None)
    try:
        return _replay_inner(store, events, speed=speed, remote=remote,
                             _sleep=_sleep, _clock=_clock)
    finally:
        _workload.install(prev_journal)


# aggregation hints reshape what "rows" means in the audit record (a
# density audit records grid mass, a stats audit sketch rows): replayed
# row counts are NOT comparable, so these events replay for latency but
# sit out the parity verdict
_AGG_HINTS = ("density", "stats", "bin")


def _replay_inner(store, events, *, speed, remote, _sleep, _clock):
    out: list[dict] = []
    t0 = _clock()
    base_arrival = events[0].get("ts_arrival", 0.0) if events else 0.0
    for e in events:
        if remote:
            blocked_hints = set(e.get("hints") or {}) - {"tenant"}
            if blocked_hints or e.get("auths") is not None:
                out.append({
                    "seq": e.get("seq"),
                    "plan_signature": e.get("plan_signature", ""),
                    "skipped": ("hints " + ",".join(sorted(blocked_hints))
                                if blocked_hints else "auths")
                               + " not forwardable over --url",
                })
                continue
        if speed:
            due = (e.get("ts_arrival", 0.0) - base_arrival) / speed
            lag = due - (_clock() - t0)
            if lag > 0:
                _sleep(lag)
        res = {
            "seq": e.get("seq"),
            "plan_signature": e.get("plan_signature", ""),
            "tenant": e.get("tenant", ""),
            "type": e.get("type", ""),
            "recorded_ms": float(e.get("latency_ms", 0.0)),
            "recorded_rows": int(e.get("rows", 0)),
        }
        try:
            q = _query_of(e)
            with _usage.tenant_context(e.get("tenant")):
                tq = _clock()
                r = store.query(e["type"], q)
                res["replayed_ms"] = (_clock() - tq) * 1000.0
            res["replayed_rows"] = int(r.count)
            if any(h in (e.get("hints") or {}) for h in _AGG_HINTS):
                # aggregation audits record grid/sketch mass, not row
                # count — latency compares, row parity abstains
                res["parity"] = None
            else:
                res["parity"] = (
                    res["replayed_rows"] == res["recorded_rows"])
        except Exception as exc:  # noqa: BLE001 — a replay surveys, not crashes
            res["error"] = f"{type(exc).__name__}: {exc}"[:200]
            res["parity"] = False
        out.append(res)
    return out


def _quantiles(vals: list[float]) -> dict:
    if not vals:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    s = sorted(vals)
    top = len(s) - 1

    def q(p: float) -> float:
        pos = p * top
        lo = int(pos)
        hi = min(lo + 1, top)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    return {"p50": round(q(0.5), 3), "p95": round(q(0.95), 3),
            "p99": round(q(0.99), 3)}


def report(events: list[dict], outcomes: list[dict],
           mode: str = "closed-loop") -> dict:
    """The recorded-vs-replayed comparison, keyed by plan signature.

    ``configs`` is the ``bench.py --regress``-loadable section: one entry
    per signature, ``value`` = replayed p50 ms, ``parity`` = every
    replayed query of that shape returned the recorded row count."""
    skipped = [o for o in outcomes if "skipped" in o]
    outcomes = [o for o in outcomes if "skipped" not in o]
    by_sig: dict[str, list[dict]] = {}
    for o in outcomes:
        by_sig.setdefault(o.get("plan_signature") or "?", []).append(o)
    sigs = {}
    configs = {}
    mismatches = []
    errors = 0
    for sig, rows in sorted(by_sig.items()):
        ok_rows = [r for r in rows if "error" not in r]
        errors += len(rows) - len(ok_rows)
        rec = _quantiles([r["recorded_ms"] for r in rows])
        rep = _quantiles([r["replayed_ms"] for r in ok_rows])
        # parity=None (aggregation-hinted events) abstains: only an
        # actual False (row mismatch / error) fails the shape
        parity = all(r.get("parity") is not False for r in rows)
        for r in rows:
            if r.get("parity") is False and len(mismatches) < 16:
                mismatches.append({
                    "seq": r.get("seq"), "signature": sig,
                    "recorded_rows": r.get("recorded_rows"),
                    "replayed_rows": r.get("replayed_rows"),
                    "error": r.get("error"),
                })
        sigs[sig] = {
            "n": len(rows),
            "recorded_ms": rec,
            "replayed_ms": rep,
            "parity": parity,
            "speedup_p50": (
                round(rec["p50"] / rep["p50"], 3) if rep["p50"] else None
            ),
        }
        configs[f"replay:{sig}"] = {
            "value": rep["p50"],
            "unit": "ms/query",
            "parity": parity,
        }
    n = len(outcomes)
    return {
        "kind": "workload-replay-report",
        "mode": mode,
        "events": n,
        "skipped": len(skipped),
        "errors": errors,
        # vacuous truth guard: a replay that issued NOTHING verified
        # nothing — it must not read as a pass in a gate. None abstains
        # (aggregation-hinted events compare latency, not row counts).
        "parity_ok": bool(outcomes) and all(
            o.get("parity") is not False for o in outcomes),
        "row_mismatches": mismatches,
        "signatures": sigs,
        "recorded_ms": _quantiles([o["recorded_ms"] for o in outcomes]),
        "replayed_ms": _quantiles(
            [o["replayed_ms"] for o in outcomes if "replayed_ms" in o]),
        "configs": configs,
    }


def run(store, path_or_dir: str, *, tenant: str | None = None,
        type_name: str | None = None, source: str | None = None,
        speed: float | None = None, limit: int | None = None,
        remote: bool = False) -> dict:
    """Load → replay → report in one call (what the CLI and the bench
    gate's smoke leg drive)."""
    events = load_events(path_or_dir, tenant=tenant, type_name=type_name,
                         source=source, limit=limit)
    outcomes = replay(store, events, speed=speed, remote=remote)
    return report(events, outcomes,
                  mode=f"open-loop x{speed}" if speed else "closed-loop")


def replay_bundle(store, path: str) -> dict:
    """Re-execute one audit repro bundle (``geomesa-tpu replay
    --bundle``): run the diverging query's live path AND the
    independent referee against ``store`` — for both the original and
    the delta-debugged minimized predicate — and report whether the
    divergence reproduces. Runs in audit shadow, so a diagnostic replay
    never trains the planner or bills a tenant."""
    from geomesa_tpu.obs import audit as _audit

    return _audit.replay_bundle(store, path)


def write_report(doc: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
