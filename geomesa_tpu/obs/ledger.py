"""Host-roundtrip ledger — per-query device-dispatch choreography accounting.

ROADMAP item 1 (whole-plan device compilation) needs evidence: WHICH plan
signatures pay for staged execution — multiple device dispatches per query
with host code (``np.asarray`` materializations, bound computations,
padding decisions) running between them — and which already run fused.
This module is that evidence plane:

- :class:`QueryLedger` — a per-query accumulator opened by the datastore
  around each query/select-many execution (:func:`roundtrip`). The jaxmon
  dispatch wrapper (:func:`geomesa_tpu.obs.jaxmon.observed`) reports every
  device dispatch into the live ledger via :func:`note_dispatch`; backend
  call sites report host sync points (``np.asarray`` on a device result —
  a ``block_until_ready`` in disguise) via :func:`materialize` /
  ``QueryLedger.note_sync``. Between consecutive device activities the
  ledger derives the INTER-STAGE HOST GAP: wall time where the device sat
  idle while host code choreographed the next dispatch.
- :class:`LedgerTable` — the bounded per-(type, plan-signature) rollup.
  ``fusion_report()`` ranks signatures by host-choreography share
  ``(host_gap_ms + sync_ms) / wall_ms`` — the work list for whole-plan
  compilation, served at ``GET /api/obs/fusion`` and
  ``geomesa-tpu obs fusion-report``.

Propagation is a ContextVar, exactly like devprof's profile context: the
context survives into the planner/backend call stack of the same logical
query, and a NESTED :func:`roundtrip` (a select-many fallback re-entering
``DataStore.query``) gets a FRESH inner ledger so the inner query's counts
are attributed to its own signature, not double-charged to the batch.

Overhead discipline: the off path (no roundtrip open — internal scans,
audit shadow traffic) costs one ContextVar read per dispatch. The on path
adds one leaf-lock acquisition per dispatch/sync against device calls that
cost milliseconds. No jax anywhere (``GEOMESA_TPU_NO_JAX=1`` safe).

Locking: ``QueryLedger`` and ``LedgerTable`` each own one leaf lock
(metrics tier, docs/concurrency.md); nothing blocking runs under either.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar

from geomesa_tpu.analysis.contracts import cache_surface, feedback_sink

__all__ = [
    "QueryLedger", "LedgerTable", "roundtrip", "current", "note_dispatch",
    "materialize", "table", "install",
    "EXPORT_KIND", "EXPORT_SCHEMA_VERSION",
]

# stable export schema consumed by `python -m geomesa_tpu.analysis --sync
# --reconcile` (analysis/sync/rules.py mirrors both constants; a version
# bump there must land together with one here). The export is raw rollup
# counters, NOT the derived fusion_report ranking — reconciliation needs
# exact dispatch totals, not shares.
EXPORT_KIND = "geomesa-tpu-roundtrip-ledger"
EXPORT_SCHEMA_VERSION = 1

_led_var: ContextVar[QueryLedger | None] = ContextVar(
    "geomesa_roundtrip_ledger", default=None)

# rollup-table cardinality cap: (type, signature) keys are bounded in
# practice (few types x few plan shapes), the cap is a safety valve against
# a pathological filter stream minting unbounded signatures
_MAX_ENTRIES = 256


class QueryLedger:
    """Per-query roundtrip accumulator. One instance per :func:`roundtrip`
    context; mutated from the query's own call stack (and, for federated
    members, pool threads carrying the copied context) — guarded by its
    own leaf lock."""

    __slots__ = ("dispatches", "compiles", "dispatch_ms", "syncs", "sync_ms",
                 "host_gap_ms", "h2d_bytes", "d2h_bytes", "_last_end",
                 "_lock")

    def __init__(self) -> None:
        self.dispatches = 0
        self.compiles = 0
        self.dispatch_ms = 0.0
        self.syncs = 0
        self.sync_ms = 0.0
        self.host_gap_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        # perf_counter stamp of the last device activity END (dispatch
        # return or sync completion); the next dispatch's start minus this
        # is the inter-stage host gap
        self._last_end = 0.0
        self._lock = threading.Lock()  # leaf: accumulator fields

    def note_dispatch(self, t0: float, t1: float, *, compiled: bool = False,
                      h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """One device dispatch spanning ``[t0, t1]`` (perf_counter secs)."""
        with self._lock:
            self.dispatches += 1
            if compiled:
                self.compiles += 1
            self.dispatch_ms += (t1 - t0) * 1000.0
            if self._last_end and t0 > self._last_end:
                self.host_gap_ms += (t0 - self._last_end) * 1000.0
            if t1 > self._last_end:
                self._last_end = t1
            self.h2d_bytes += h2d_bytes
            self.d2h_bytes += d2h_bytes

    def note_sync(self, t0: float, t1: float) -> None:
        """One host sync point (``np.asarray`` / ``block_until_ready`` on a
        device result) spanning ``[t0, t1]``. The wait itself is device
        drain, not host choreography — but its END restarts the gap clock:
        host code after the sync up to the next dispatch is choreography."""
        with self._lock:
            self.syncs += 1
            self.sync_ms += (t1 - t0) * 1000.0
            if self._last_end and t0 > self._last_end:
                self.host_gap_ms += (t0 - self._last_end) * 1000.0
            if t1 > self._last_end:
                self._last_end = t1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "compiles": self.compiles,
                "dispatch_ms": self.dispatch_ms,
                "syncs": self.syncs,
                "sync_ms": self.sync_ms,
                "host_gap_ms": self.host_gap_ms,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
            }


def current() -> QueryLedger | None:
    """The live ledger, if a roundtrip context is open on this
    context-propagation chain (None on the off path)."""
    return _led_var.get()


@contextmanager
def roundtrip():
    """Open a per-query ledger for the enclosed execution. Always a FRESH
    ledger: a nested roundtrip (select-many fallback re-entering
    ``DataStore.query``) attributes to its own signature. Yields the
    :class:`QueryLedger` so the closer can charge it to the rollup."""
    ql = QueryLedger()
    tok = _led_var.set(ql)
    try:
        yield ql
    finally:
        _led_var.reset(tok)


def note_dispatch(t0: float, t1: float, *, compiled: bool = False,
                  h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
    """Module-level dispatch hook for the jaxmon wrapper: one ContextVar
    read on the off path, one locked accumulate on the on path."""
    ql = _led_var.get()
    if ql is not None:
        ql.note_dispatch(t0, t1, compiled=compiled,
                         h2d_bytes=h2d_bytes, d2h_bytes=d2h_bytes)


def materialize(obj):
    """``np.asarray`` with sync-point accounting: the canonical way for a
    backend to pull a device result to host. Off path (no ledger open)
    degrades to a bare ``np.asarray``."""
    import numpy as np

    ql = _led_var.get()
    if ql is None:
        return np.asarray(obj)
    t0 = time.perf_counter()
    out = np.asarray(obj)
    ql.note_sync(t0, time.perf_counter())
    return out


class _Rollup:
    """One (type, plan-signature) rollup row."""

    __slots__ = ("queries", "dispatches", "compiles", "dispatch_ms", "syncs",
                 "sync_ms", "host_gap_ms", "wall_ms", "h2d_bytes",
                 "d2h_bytes")

    def __init__(self) -> None:
        self.queries = 0
        self.dispatches = 0
        self.compiles = 0
        self.dispatch_ms = 0.0
        self.syncs = 0
        self.sync_ms = 0.0
        self.host_gap_ms = 0.0
        self.wall_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0


@cache_surface(name="roundtrip-ledger", keyed_by="type_name",
               purge=("forget",))
class LedgerTable:
    """Bounded per-(type, plan-signature) roundtrip rollup. Entries for a
    dropped/renamed type are purged via :meth:`forget` alongside the cost
    table (``DataStore._purge_type_name``) — stale signatures must not
    keep ranking in the fusion report after their schema is gone."""

    def __init__(self, max_entries: int = _MAX_ENTRIES):
        self._lock = threading.Lock()  # leaf: rollup table
        self._max = max_entries
        self._rows: dict[tuple[str, str], _Rollup] = {}

    @feedback_sink
    def charge(self, type_name: str, signature: str, ql: QueryLedger,
               wall_ms: float) -> None:
        """Fold one query's ledger into the (type, signature) rollup. A
        coalesced batch charges the SHARED ledger once per member query —
        every signature served by the batched dispatch sees its counts."""
        snap = ql.snapshot()
        key = (type_name, signature)
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                if len(self._rows) >= self._max:
                    # drop the coldest row (fewest queries) — cardinality
                    # valve, not an accuracy surface
                    coldest = min(self._rows, key=lambda k: self._rows[k].queries)
                    del self._rows[coldest]
                row = self._rows[key] = _Rollup()
            row.queries += 1
            row.dispatches += snap["dispatches"]
            row.compiles += snap["compiles"]
            row.dispatch_ms += snap["dispatch_ms"]
            row.syncs += snap["syncs"]
            row.sync_ms += snap["sync_ms"]
            row.host_gap_ms += snap["host_gap_ms"]
            row.wall_ms += max(wall_ms, 0.0)
            row.h2d_bytes += snap["h2d_bytes"]
            row.d2h_bytes += snap["d2h_bytes"]

    def forget(self, type_name: str) -> None:
        """Purge every rollup row for ``type_name`` (schema delete/rename)."""
        with self._lock:
            for key in [k for k in self._rows if k[0] == type_name]:
                del self._rows[key]

    def fusion_report(self, limit: int = 50) -> list[dict]:
        """Plan signatures ranked by host-choreography share — the fraction
        of wall time spent in inter-stage host gaps plus sync waits. High
        share + multiple dispatches per query = a fusion opportunity
        (ROADMAP item 1 work list)."""
        with self._lock:
            items = list(self._rows.items())
        out = []
        for (type_name, sig), row in items:
            if row.queries == 0:
                continue
            wall = max(row.wall_ms, row.dispatch_ms + row.sync_ms
                       + row.host_gap_ms, 1e-9)
            share = min(1.0, (row.host_gap_ms + row.sync_ms) / wall)
            out.append({
                "type": type_name,
                "signature": sig,
                "queries": row.queries,
                "dispatches_per_query": row.dispatches / row.queries,
                "syncs_per_query": row.syncs / row.queries,
                "compiles": row.compiles,
                "host_gap_ms": round(row.host_gap_ms, 3),
                "sync_ms": round(row.sync_ms, 3),
                "dispatch_ms": round(row.dispatch_ms, 3),
                "wall_ms": round(row.wall_ms, 3),
                "host_share": round(share, 4),
                "h2d_bytes": row.h2d_bytes,
                "d2h_bytes": row.d2h_bytes,
            })
        out.sort(key=lambda r: (-r["host_share"], -r["wall_ms"]))
        return out[:limit]

    def snapshot(self) -> dict:
        return {"entries": self.fusion_report(limit=_MAX_ENTRIES)}

    def export(self) -> dict:
        """The stable reconcile-export document (``obs ledger-export``,
        ``GET /api/obs/ledger?format=json``): one entry per (type, plan
        signature) with the raw rollup counters. Consumers key off
        ``kind`` + ``schema_version`` and must reject anything else."""
        with self._lock:
            items = sorted(self._rows.items())
        return {
            "kind": EXPORT_KIND,
            "schema_version": EXPORT_SCHEMA_VERSION,
            "entries": [
                {
                    "type": type_name,
                    "signature": sig,
                    "queries": row.queries,
                    "dispatches": row.dispatches,
                    "compiles": row.compiles,
                    "syncs": row.syncs,
                    "dispatch_ms": round(row.dispatch_ms, 3),
                    "sync_ms": round(row.sync_ms, 3),
                    "host_gap_ms": round(row.host_gap_ms, 3),
                    "wall_ms": round(row.wall_ms, 3),
                    "h2d_bytes": row.h2d_bytes,
                    "d2h_bytes": row.d2h_bytes,
                }
                for (type_name, sig), row in items
            ],
        }


_table = LedgerTable()


def table() -> LedgerTable:
    """The process-wide rollup table."""
    return _table


def install(tbl: LedgerTable) -> LedgerTable:
    """Swap the process-wide table (tests); returns the previous one."""
    global _table
    prev = _table
    _table = tbl
    return prev
