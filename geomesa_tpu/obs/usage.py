"""Tenant-attributed usage metering — the accounting half of the usage &
workload plane (docs/observability.md § Usage metering & workload replay).

GeoMesa's audit tier records every query WITH the calling identity
(``AuditProvider``/``AuditWriter`` — PAPER.md §1's index-api layer); the
reproduction's telemetry was rich per-query but anonymous. This module
closes that gap: every completed query is attributed to a *tenant* — the
``X-Geomesa-Tenant`` header (or auth-context principal) the web layer
extracted, :data:`DEFAULT_TENANT` for anonymous traffic — and accumulates
into

- per-tenant rolling-window counters (queries, rows, bytes_out, wall-ms,
  and devprof device-ms) over the same 10 s bucket scheme as
  :mod:`geomesa_tpu.obs.slo`, plus lifetime totals;
- a :class:`SpaceSaving` top-K heavy-hitter sketch over
  ``(tenant, type, plan-signature)`` weighted by wall-ms, so "which
  tenant/query-shape is burning the budget" is answerable in O(K)
  counters no matter how many distinct shapes flow through;
- per-tenant SLO objectives riding the existing
  :class:`~geomesa_tpu.obs.slo.SloEngine` (objective ``tenant.query``
  keyed by tenant) — burn rates and error budgets per tenant, the signal
  ROADMAP item 4's admission controller will shed traffic by.

Read surfaces: ``GET /api/obs/tenants`` (:meth:`UsageMeter.snapshot`),
``geomesa-tpu obs tenants`` (CLI), and ``geomesa_tenant_*{tenant=...}``
gauges appended to ``GET /api/metrics?format=prometheus`` with BOUNDED
label cardinality: the top-K tenants by window wall-ms get their own
series, everything else rolls up into ``tenant="other"`` — the scrape can
never exceed K+1 label values per metric regardless of tenant churn.

Tenant context: the web layer binds the request's tenant to a ContextVar
(:func:`tenant_context`); the store's ``_audit`` reads it (after an
explicit ``hints["tenant"]``), and :mod:`geomesa_tpu.resilience.http`
propagates it on federated RPCs as ``X-Geomesa-Tenant`` so member-side
records attribute to the ORIGINAL caller, not the federation frontend.

Locking: one leaf lock guards the tenant table + sketch (metrics tier in
docs/concurrency.md — never nested inside another lock, no blocking calls
under it; the SLO engine owns its own leaf lock). No jax anywhere
(``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar

from geomesa_tpu.analysis.contracts import feedback_sink

__all__ = [
    "DEFAULT_TENANT", "TENANT_HEADER", "TENANT_K_ENV", "SpaceSaving",
    "UsageMeter", "current_tenant", "get", "install", "observe",
    "tenant_context",
]

# the trusted tenant header (web layer + resilience/http propagation);
# WSGI spells it HTTP_X_GEOMESA_TENANT
TENANT_HEADER = "X-Geomesa-Tenant"
DEFAULT_TENANT = "anonymous"
# top-K size for the heavy-hitter sketch AND the prometheus label bound
TENANT_K_ENV = "GEOMESA_TPU_TENANT_K"

_BUCKET_S = 10.0  # rolling-counter granularity (matches obs/slo.py)
_WINDOWS = (300.0, 3600.0)  # 5m / 1h
# counter fields, in bucket-array order
_FIELDS = ("queries", "rows", "bytes_out", "wall_ms", "device_ms")

# request-scoped tenant identity (set by the web layer / replay harness;
# read by DataStore._audit and resilience.http)
_tenant_var: ContextVar[str | None] = ContextVar("geomesa_tenant",
                                                 default=None)


def current_tenant(default: str | None = DEFAULT_TENANT) -> str | None:
    """The tenant bound to this context; ``default`` when unbound."""
    t = _tenant_var.get()
    return t if t else default


@contextmanager
def tenant_context(tenant: str | None):
    """Bind ``tenant`` for the duration of a request / replayed query.
    ``None``/empty binds nothing (the ambient tenant, if any, persists)."""
    if not tenant:
        yield
        return
    tok = _tenant_var.set(str(tenant))
    try:
        yield
    finally:
        _tenant_var.reset(tok)


def escape_label(v: str) -> str:
    """Prometheus text-exposition label-value escaping (backslash, quote,
    newline). Tenant ids come from a CLIENT-controlled header — an
    unescaped ``"`` would malform the whole scrape payload, which strict
    consumers reject wholesale."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def env_k() -> int:
    """The configured top-K (sketch capacity and prometheus label bound);
    clamped to [1, 1024]."""
    try:
        k = int(os.environ.get(TENANT_K_ENV, "16"))
    except ValueError:
        k = 16
    return min(max(k, 1), 1024)


# -- SpaceSaving heavy hitters ------------------------------------------------

class SpaceSaving:
    """Metwally et al.'s SpaceSaving sketch: exactly ``capacity`` monitored
    keys; an unmonitored arrival evicts the current minimum and inherits
    its count as overestimation ``error``. Guarantees: every key with true
    weight > W/capacity (W = total weight seen) is monitored, and each
    reported count overestimates the true weight by at most its recorded
    ``error``. NOT thread-safe on its own — the owning meter's lock guards
    every offer/read."""

    __slots__ = ("capacity", "_counts", "_errors", "total")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._counts: dict = {}
        self._errors: dict = {}
        self.total = 0.0

    def offer(self, key, weight: float = 1.0) -> None:
        self.total += weight
        c = self._counts.get(key)
        if c is not None:
            self._counts[key] = c + weight
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = weight
            self._errors[key] = 0.0
            return
        # evict the minimum; the newcomer inherits its count as error
        mk = min(self._counts, key=self._counts.__getitem__)
        mv = self._counts.pop(mk)
        self._errors.pop(mk)
        self._counts[key] = mv + weight
        self._errors[key] = mv

    def top(self, k: int | None = None) -> list:
        """``[(key, count, error)]`` sorted by count descending; ``count``
        overestimates the true weight by at most ``error``."""
        items = sorted(self._counts.items(), key=lambda kv: -kv[1])
        if k is not None:
            items = items[:k]
        return [(key, c, self._errors[key]) for key, c in items]


# -- per-tenant rolling counters ----------------------------------------------

class _TenantUsage:
    """Bucketed rolling counters + lifetime totals for one tenant. Bucket
    mutation is guarded by the OWNING meter's lock."""

    __slots__ = ("tenant", "_buckets", "lifetime", "last_seen")

    def __init__(self, tenant: str):
        self.tenant = tenant
        # (bucket_start_s, [queries, rows, bytes_out, wall_ms, device_ms]),
        # oldest first, pruned to the longest window on append
        self._buckets: list = []
        self.lifetime = [0, 0, 0, 0.0, 0.0]
        self.last_seen = 0.0

    def _observe_locked(self, now: float, queries: int, rows: int,
                        bytes_out: int, wall_ms: float,
                        device_ms: float) -> None:
        self.last_seen = now
        vals = (queries, rows, bytes_out, wall_ms, device_ms)
        for i, v in enumerate(vals):
            self.lifetime[i] += v
        start = now - (now % _BUCKET_S)
        if self._buckets and self._buckets[-1][0] == start:
            b = self._buckets[-1][1]
            for i, v in enumerate(vals):
                b[i] += v
        else:
            self._buckets.append((start, list(vals)))
            horizon = now - max(_WINDOWS) - _BUCKET_S
            while self._buckets and self._buckets[0][0] < horizon:
                self._buckets.pop(0)

    def window_locked(self, window_s: float, now: float) -> dict:
        lo = now - window_s
        acc = [0, 0, 0, 0.0, 0.0]
        for start, vals in self._buckets:
            if start + _BUCKET_S > lo:
                for i, v in enumerate(vals):
                    acc[i] += v
        return dict(zip(_FIELDS, acc))


# -- the meter ----------------------------------------------------------------

class UsageMeter:
    """Process-wide per-tenant usage accounting.

    ``observe`` is the always-on hot path: ONE lock acquisition for the
    tenant bucket + sketch update, plus one (own-leaf-lock) SLO engine
    observation — the same cost class as the flight recorder append, so
    the <2% cached-select bound holds with metering on
    (``tests/test_usage_workload.py``).

    The tenant table is bounded (``max_tenants``): past the cap the
    least-recently-seen tenant folds its LIFETIME totals into the
    ``other`` rollup and is dropped — an unbounded tenant-id stream (a
    misbehaving client minting fresh ids) cannot grow process memory.
    """

    OTHER = "other"

    def __init__(self, k: int | None = None, max_tenants: int = 256,
                 slo=None, slo_target: float = 0.999,
                 slo_latency_ms: float | None = 1000.0,
                 clock=time.time):
        self.k = k if k is not None else env_k()
        self.max_tenants = max(max_tenants, self.k + 1)
        self._clock = clock
        self._lock = threading.Lock()  # leaf: tenant table + sketch
        self._tenants: dict[str, _TenantUsage] = {}
        # lifetime totals folded out of evicted tenants (the "other" row)
        self._other = _TenantUsage(self.OTHER)
        self._sketch = SpaceSaving(self.k)
        if slo is None:
            from geomesa_tpu.obs.slo import SloEngine

            slo = SloEngine()
        self.slo = slo
        self.slo.objective("tenant.query", target=slo_target,
                           latency_ms=slo_latency_ms)
        self.observe_count = 0

    # -- hot path -------------------------------------------------------------
    @feedback_sink
    def observe(self, tenant: str | None, type_name: str, signature: str,
                *, rows: int = 0, bytes_out: int = 0, wall_ms: float = 0.0,
                device_ms: float = 0.0, ok: bool = True,
                slo: bool = True) -> None:
        """Account one completed query. ``device_ms`` is the devprof
        attribution total when the query was sampled (0 otherwise — the
        per-tenant device-ms series is a sampled estimate, reconciling
        with devmon's own attribution within the sampling error).
        ``slo=False`` skips the tenant's SLO observation: admission
        SHEDS are metered this way — a shed feeding back into the very
        budget that caused it would lock the tenant out forever
        (docs/serving.md § Admission)."""
        t = str(tenant) if tenant else DEFAULT_TENANT
        now = self._clock()
        with self._lock:
            u = self._tenants.get(t)
            if u is None:
                u = self._tenants[t] = _TenantUsage(t)
            # observe BEFORE any eviction: a just-created tenant has the
            # newest last_seen, so the fold-out always takes the oldest
            u._observe_locked(now, 1, int(rows), int(bytes_out),
                              float(wall_ms), float(device_ms))
            evicted = (self._evict_locked()
                       if len(self._tenants) > self.max_tenants else None)
            self._sketch.offer((t, type_name, signature),
                               max(float(wall_ms), 0.0))
            self.observe_count += 1
        # per-tenant SLO (own leaf lock, taken strictly AFTER ours is
        # released): a slow query burns the tenant's latency budget — what
        # admission control will shed by. Evicting a tenant drops its
        # tracker too, so the engine (and its exposition) stays bounded by
        # the table cap even under an unbounded tenant-id stream.
        if evicted is not None:
            self.slo.forget("tenant.query", evicted)
        if slo:
            self.slo.observe("tenant.query", ok=ok, latency_ms=wall_ms,
                             key=t)

    def note_bytes_out(self, tenant: str | None, nbytes: int) -> None:
        """Attribute response payload bytes (the web layer's serialized
        size — the store cannot know it) to a tenant without counting a
        query."""
        t = str(tenant) if tenant else DEFAULT_TENANT
        now = self._clock()
        with self._lock:
            u = self._tenants.get(t)
            if u is None:
                u = self._tenants[t] = _TenantUsage(t)
            u._observe_locked(now, 0, 0, int(nbytes), 0.0, 0.0)
            evicted = (self._evict_locked()
                       if len(self._tenants) > self.max_tenants else None)
        if evicted is not None:
            self.slo.forget("tenant.query", evicted)

    def _evict_locked(self) -> str:
        """Fold the least-recently-seen tenant into ``other``; returns
        the evicted tenant id (its SLO tracker is dropped by the caller
        OUTSIDE this lock)."""
        victim = min(self._tenants.values(), key=lambda u: u.last_seen)
        del self._tenants[victim.tenant]
        for i, v in enumerate(victim.lifetime):
            self._other.lifetime[i] += v
        return victim.tenant

    # -- read surfaces --------------------------------------------------------
    def _ranked_locked(self, now: float) -> list:
        """Tenants ranked by 5m-window wall-ms (ties: lifetime wall-ms) —
        the ordering both the snapshot and the prometheus top-K use."""
        return sorted(
            self._tenants.values(),
            key=lambda u: (-u.window_locked(_WINDOWS[0], now)["wall_ms"],
                           -u.lifetime[3], u.tenant),
        )

    def snapshot(self, limit: int | None = None) -> dict:
        """The ``GET /api/obs/tenants`` payload: per-tenant window +
        lifetime counters (ranked by recent wall-ms), the heavy-hitter
        table, and per-tenant SLO burn/budget."""
        now = self._clock()
        with self._lock:
            ranked = self._ranked_locked(now)
            if limit is not None:
                ranked = ranked[:limit]
            tenants = []
            for u in ranked:
                tenants.append({
                    "tenant": u.tenant,
                    "windows": {
                        _wlabel(w): u.window_locked(w, now) for w in _WINDOWS
                    },
                    "lifetime": dict(zip(_FIELDS, list(u.lifetime))),
                })
            hitters = [
                {"tenant": key[0], "type": key[1], "signature": key[2],
                 "wall_ms": round(c, 3), "error_ms": round(err, 3)}
                for key, c, err in self._sketch.top()
            ]
            other = dict(zip(_FIELDS, list(self._other.lifetime)))
            n_tenants = len(self._tenants)
            observed = self.observe_count
            sketch_total = self._sketch.total
        # SLO section OUTSIDE the meter lock (engine owns its own)
        for t in tenants:
            tk = self.slo.tracker("tenant.query", t["tenant"])
            t["slo"] = {
                "burn_rate_5m": tk.burn_rate(300.0),
                "budget_remaining_5m": tk.budget_remaining(300.0),
            }
        return {
            "tenants": tenants,
            "tenant_count": n_tenants,
            "other_lifetime": other,
            "heavy_hitters": hitters,
            "heavy_hitter_total_ms": round(sketch_total, 3),
            "k": self.k,
            "observe_count": observed,
        }

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        """``geomesa_tenant_*`` gauges with a ``tenant`` label, bounded to
        K+1 label values: the top-K tenants by recent wall-ms plus one
        ``other`` rollup summing every remaining tenant AND the evicted
        fold-in — totals reconcile with the unlabeled counters exactly.
        The per-tenant SLO burn/budget gauges (``geomesa_tenant_slo_*``,
        distinct metric names so the store engine's ``geomesa_slo_*``
        ``# TYPE`` headers are never duplicated) are emitted for the SAME
        top-K tenants only — the K+1 cardinality bound holds across
        every ``geomesa_tenant_*`` series, not just the counters."""
        now = self._clock()
        with self._lock:
            if not self._tenants and not self._other.lifetime[0]:
                return []
            ranked = self._ranked_locked(now)
            top, rest = ranked[:self.k], ranked[self.k:]
            rows = [(u.tenant, list(u.lifetime)) for u in top]
            other = list(self._other.lifetime)
            for u in rest:
                for i, v in enumerate(u.lifetime):
                    other[i] += v
        rows.append((self.OTHER, other))
        names = ("queries_total", "rows_total", "bytes_out_total",
                 "wall_ms_total", "device_ms_total")
        lines: list[str] = []
        for i, name in enumerate(names):
            metric = f"{prefix}_tenant_{name}"
            lines.append(f"# TYPE {metric} counter")
            for tenant, vals in rows:
                v = vals[i]
                v = f"{v:.6g}" if isinstance(v, float) else str(v)
                lines.append(
                    f'{metric}{{tenant="{escape_label(tenant)}"}} {v}')
        burn = [f"# TYPE {prefix}_tenant_slo_burn_rate gauge"]
        budget = [f"# TYPE {prefix}_tenant_slo_budget_remaining gauge"]
        for tenant, _ in rows[:-1]:  # top-K only; "other" has no tracker
            tk = self.slo.tracker("tenant.query", tenant)
            for w in tk.objective.windows:
                lbl = (f'tenant="{escape_label(tenant)}",'
                       f'window="{_wlabel(w)}"')
                burn.append(
                    f"{prefix}_tenant_slo_burn_rate{{{lbl}}} "
                    f"{tk.burn_rate(w):.6g}")
                budget.append(
                    f"{prefix}_tenant_slo_budget_remaining{{{lbl}}} "
                    f"{tk.budget_remaining(w):.6g}")
        lines.extend(burn)
        lines.extend(budget)
        return lines

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        lines = self.prometheus_lines(prefix)
        return "\n".join(lines) + "\n" if lines else ""


def _wlabel(w: float) -> str:
    from geomesa_tpu.obs.slo import window_label

    return window_label(w)


# -- process-wide meter -------------------------------------------------------

_meter = UsageMeter()


def get() -> UsageMeter:
    return _meter


def install(meter: UsageMeter) -> UsageMeter:
    """Swap the process meter (test isolation); returns the previous."""
    global _meter
    prev, _meter = _meter, meter
    return prev


@feedback_sink
def observe(tenant: str | None, type_name: str, signature: str, *,
            rows: int = 0, bytes_out: int = 0, wall_ms: float = 0.0,
            device_ms: float = 0.0, ok: bool = True,
            slo: bool = True) -> None:
    """Module-level hot-path helper (what ``DataStore._audit`` calls)."""
    _meter.observe(tenant, type_name, signature, rows=rows,
                   bytes_out=bytes_out, wall_ms=wall_ms,
                   device_ms=device_ms, ok=ok, slo=slo)
