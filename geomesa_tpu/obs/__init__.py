"""geomesa_tpu.obs — end-to-end query observability.

Three layers (see docs/observability.md):

- :mod:`~geomesa_tpu.obs.trace` — hierarchical spans with ContextVar
  propagation and a zero-overhead no-op path when disabled.
- :mod:`~geomesa_tpu.obs.jaxmon` — JAX compile/dispatch telemetry: per-step
  jit timing, recompile counts keyed by abstract signature (live J003),
  host↔device transfer bytes.
- :mod:`~geomesa_tpu.obs.export` — Chrome/Perfetto trace-event JSON and
  Prometheus text exposition.

This package imports no jax at module level: ``GEOMESA_TPU_NO_JAX=1``
processes (tpulint in CI) can import every instrumented module.
"""

from geomesa_tpu.obs.trace import (  # noqa: F401 — the public obs surface
    NOOP,
    Span,
    StageTimeline,
    active,
    annotate,
    collect,
    current,
    disable,
    enable,
    enabled,
    event,
    drain,
    recent,
    span,
)

__all__ = [
    "NOOP", "Span", "StageTimeline", "active", "annotate", "collect",
    "current", "disable", "enable", "enabled", "event", "drain", "recent",
    "span",
]
