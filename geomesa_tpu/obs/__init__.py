"""geomesa_tpu.obs — end-to-end query observability.

Six layers (see docs/observability.md):

- :mod:`~geomesa_tpu.obs.trace` — hierarchical spans with ContextVar
  propagation, a zero-overhead no-op path when disabled, and the
  federation trace contract (``X-Geomesa-Trace`` inject/extract, span
  subtree serialize/graft for stitched cross-process trees).
- :mod:`~geomesa_tpu.obs.jaxmon` — JAX compile/dispatch telemetry: per-step
  jit timing, recompile counts keyed by abstract signature (live J003),
  host↔device transfer bytes.
- :mod:`~geomesa_tpu.obs.export` — Chrome/Perfetto trace-event JSON and
  Prometheus text exposition.
- :mod:`~geomesa_tpu.obs.flight` — the always-on query-audit flight
  recorder (bounded ring + anomaly dumps).
- :mod:`~geomesa_tpu.obs.slo` — SLO objectives, multi-window burn rates,
  error-budget exposition.
- :mod:`~geomesa_tpu.obs.devmon` — device telemetry: the HBM residency
  ledger, sampled per-query device-time attribution (devprof), and the
  per-(type, plan-signature) observed-cost table.
- :mod:`~geomesa_tpu.obs.usage` — tenant-attributed usage metering:
  per-tenant rolling counters, the (tenant, type, plan-signature)
  heavy-hitter sketch, per-tenant SLOs, bounded-cardinality exposition.
- :mod:`~geomesa_tpu.obs.workload` / :mod:`~geomesa_tpu.obs.replay` —
  workload capture (one JSONL wide event per query) and the
  deterministic replay harness with recorded-vs-replayed reports.
- :mod:`~geomesa_tpu.obs.lens` — the retained profiling plane: per
  (type, plan-signature) time-bucketed latency histograms with trace
  exemplars, true Prometheus histogram families, and the live
  regression sentinel (``A_REGRESSION``).
- :mod:`~geomesa_tpu.obs.ledger` — the host-roundtrip ledger: per-query
  dispatch/sync/host-gap accounting rolled up into the per-signature
  fusion-opportunity report.

This package imports no jax at module level: ``GEOMESA_TPU_NO_JAX=1``
processes (tpulint in CI) can import every instrumented module.
"""

from geomesa_tpu.obs.trace import (  # noqa: F401 — the public obs surface
    NOOP,
    TRACE_HEADER,
    TRACE_RETURN_HEADER,
    Span,
    StageTimeline,
    TraceContext,
    active,
    annotate,
    collect,
    current,
    disable,
    enable,
    enabled,
    event,
    extract,
    drain,
    graft_serialized,
    inject,
    propagated,
    recent,
    serialize_subtree,
    span,
)

__all__ = [
    "NOOP", "Span", "StageTimeline", "active", "annotate", "collect",
    "current", "disable", "enable", "enabled", "event", "drain", "recent",
    "span", "TRACE_HEADER", "TRACE_RETURN_HEADER", "TraceContext",
    "extract", "graft_serialized", "inject", "propagated",
    "serialize_subtree",
]
