"""stream-lens: per-(topic, subscription) delivery observability.

The query plane's retained lens (:mod:`geomesa_tpu.obs.lens`) answers
"since when is signature X slow, show me one trace"; this module is the
same retained plane for STANDING queries — what makes a 1M-subscription
registry operable (ROADMAP item 4):

- :class:`StreamLens` — per (topic, subscription) series on the shared
  :class:`~geomesa_tpu.obs.lens.HistogramRing` base (same ring / valve /
  exemplar machinery as the query lens, so the two planes cannot drift).
  Each delivery records the processing-time latency from bus append to
  ``HitBatch`` delivery, DECOMPOSED from the per-chunk stage stamps the
  scanner carries (:data:`STAGES`: queue-wait / pad-flush-wait / H2D
  staging / fused scan / host refine / fan-out), plus event-time
  on-time/late accounting per watermark advance and chunk trace-id
  exemplars that resolve to stitched span trees
  (``GET /api/obs/stream?trace=``).
- per-subscription COST attribution folded out of outputs the fused scan
  already computes: ``cost = hits + refine_rows + 0.01 × chunk_rows``
  (delivered hit rows and wide-row envelope-refine rows at full weight;
  the subscription's equal per-slot share of the fused ``rows × queries``
  pass down-weighted — occupancy is paid by every slot alike, matching
  is what differentiates subscriptions). The scale report ranks by the
  share of this.
- the capacity section: per-topic matrix occupancy / epoch churn rate /
  predicted next bucket-crossing recompile / HBM bytes-per-subscription
  extrapolated to 1M — fed by :meth:`StreamLens.note_matrix` once per
  scanned chunk.
- a ``stream.delivery`` SLO per topic on the lens's own
  :class:`~geomesa_tpu.obs.slo.SloEngine` (the usage-meter pattern: own
  engine, distinct metric names so ``# TYPE`` headers never collide with
  the store engine's), burned by late or slow deliveries.
- :class:`BacklogSentinel` — the ISSUE-17 ``RegressionSentinel`` shape:
  a shadow-plane comparator latching ONE ``A_BACKLOG`` flight anomaly
  per episode when a topic's watermark freshness, scanner queue depth,
  or delivery-SLO burn rate sustains past threshold.

Valve: unlike the query lens (longest-idle eviction), the stream lens
evicts the CHEAPEST series at the cardinality bound and folds it into a
per-topic ``other`` rollup, so totals stay reconcilable and the
Prometheus surface (``geomesa_stream_delivery_*``) stays bounded AND
representative at high subscription counts. The same top-K-by-cost
ranking bounds the watermark/freshness gauges in
:mod:`geomesa_tpu.stream.telemetry`.

Overhead discipline: ``observe_delivery`` is on the always-on scan path —
one leaf-lock acquisition per (subscription × chunk), a bisect into the
shared fixed edges, and a handful of increments (the ≤2% fused-scan bound
is pinned in tests/test_streamlens.py). No jax anywhere
(``GEOMESA_TPU_NO_JAX=1`` safe).

Locking (docs/concurrency.md): the lens lock (via HistogramRing) and the
sentinel's state lock are LEAVES — nothing is called while either is
held; the SLO engine's lock is its own leaf underneath.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque

from geomesa_tpu.analysis.contracts import (cache_surface, feedback_sink,
                                            shadow_plane)
from geomesa_tpu.obs.lens import (BUCKET_EDGES_MS, _BUCKET_S, _MAX_SERIES,
                                  _N_BINS, _RING, _esc, _fmt_le, _quantile,
                                  HistogramRing, _LensBucket, _Series)
from geomesa_tpu.obs.slo import SloEngine

__all__ = [
    "StreamLens", "BacklogSentinel", "STAGES", "get", "install",
    "sentinel", "install_sentinel",
]

# the stage decomposition contract (docs/streaming.md § Stream lens):
# bus append → HitBatch delivery, in pipeline order. Stamped per CHUNK by
# the scanner, attributed per delivery.
STAGES = ("queue_wait", "pad_flush", "h2d", "scan", "refine", "fanout")
_N_STAGES = len(STAGES)

# cost-attribution weight of one fused-scan row-evaluation relative to
# one delivered/refined hit row (see module docstring)
SCAN_ROW_WEIGHT = 0.01

# exposition bound: series per topic emitted individually; the rest
# aggregate into the `other` rollup (also the watermark-gauge bound —
# stream/telemetry.py imports this)
TOP_K = 64


class _DeliveryBucket(_LensBucket):
    """One time bucket of one delivery series: the shared latency
    histogram plus the stream plane's extra counters. ``rows`` counts
    delivered hit rows, ``dispatches`` counts scanned chunks."""

    __slots__ = ("on_time", "late", "stage_ms", "cost")

    def __init__(self, start: float):
        super().__init__(start)
        self.on_time = 0  # watermark advances whose window was on-time
        self.late = 0  # advances containing rows behind the watermark
        self.stage_ms = [0.0] * _N_STAGES
        self.cost = 0.0


class _DeliverySeries(_Series):
    """Ring plus LIFETIME rollups (the cost ranking and the report read
    these without merging the ring)."""

    __slots__ = ("cost", "hit_rows", "chunks", "deliveries", "on_time",
                 "late", "stage_ms")

    def __init__(self, ring: int = _RING):
        super().__init__(ring)
        self.cost = 0.0
        self.hit_rows = 0
        self.chunks = 0
        self.deliveries = 0
        self.on_time = 0
        self.late = 0
        self.stage_ms = [0.0] * _N_STAGES


class _TopicState:
    """Per-topic capacity/churn observations + the valve's ``other``
    rollup + dropped-row accounting. Mutated under the lens lock."""

    __slots__ = ("ring", "slot_bytes", "dropped_rows", "dropped_chunks",
                 "other")

    def __init__(self):
        # (ts, epoch, active, capacity) — churn + growth trend source
        self.ring: deque = deque(maxlen=_RING)
        self.slot_bytes = 0
        self.dropped_rows = 0
        self.dropped_chunks = 0
        # valve rollup of evicted series: totals stay reconcilable
        self.other = {"series": 0, "cost": 0.0, "hit_rows": 0,
                      "deliveries": 0, "on_time": 0, "late": 0}


@cache_surface(name="stream-lens", keyed_by="topic", purge=("forget",))
class StreamLens(HistogramRing):
    """Per-(topic, subscription) delivery histograms with stage
    decomposition, lateness accounting, cost attribution, and the
    standing-query scale report."""

    _bucket_cls = _DeliveryBucket
    _series_cls = _DeliverySeries

    def __init__(self, bucket_s: float = _BUCKET_S, ring: int = _RING,
                 max_series: int = _MAX_SERIES, clock=time.time,
                 slo_target: float = 0.999,
                 slo_latency_ms: float = 2500.0):
        super().__init__(bucket_s=bucket_s, ring=ring,
                         max_series=max_series, clock=clock)
        self._topics: dict[str, _TopicState] = {}
        # own engine, usage-meter pattern: stream.delivery burn must not
        # share trackers (or # TYPE headers) with the store's engine
        self.slo = SloEngine()
        self.slo.objective("stream.delivery", target=slo_target,
                           latency_ms=slo_latency_ms)

    # -- valve ---------------------------------------------------------------
    def _evict_locked(self) -> None:
        """Top-K-by-cost valve: evict the CHEAPEST series and fold its
        lifetime totals into its topic's ``other`` rollup (the query
        lens's longest-idle policy would evict a quiet-but-expensive
        subscription the report must keep ranking)."""
        key = min(self._series, key=lambda k: self._series[k].cost)
        s = self._series.pop(key)
        o = self._topic_locked(key[0]).other
        o["series"] += 1
        o["cost"] += s.cost
        o["hit_rows"] += s.hit_rows
        o["deliveries"] += s.deliveries
        o["on_time"] += s.on_time
        o["late"] += s.late

    def _topic_locked(self, topic: str) -> _TopicState:
        st = self._topics.get(topic)
        if st is None:
            st = self._topics[topic] = _TopicState()
        return st

    # -- the hot path ---------------------------------------------------------
    @feedback_sink
    def observe_delivery(self, topic: str, subscription, *,
                         latency_ms: float | None = None,
                         stages: tuple | None = None, hit_rows: int = 0,
                         cost: float = 0.0, on_time: bool | None = None,
                         trace_id: str = "", now: float | None = None) -> None:
        """One (subscription × scanned chunk) observation. Always-on:
        one lock, one bisect, a few increments. ``latency_ms`` is None
        when the chunk matched nothing for this subscription (cost and
        watermark accounting still land; the histogram only ever holds
        real deliveries). ``on_time`` is None when the topic carries no
        event time (packed-payload matrices)."""
        if now is None:
            now = self._clock()
        key = (topic, str(subscription))
        bin_i = (bisect_left(BUCKET_EDGES_MS, latency_ms)
                 if latency_ms is not None else 0)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                # touch the topic table first so the valve's rollup
                # target exists before any eviction can need it
                self._topic_locked(topic)
            b = self._bucket_locked(key, now)
            series = self._series[key]
            series.chunks += 1
            b.dispatches += 1
            series.cost += cost
            b.cost += cost
            if on_time is not None:
                if on_time:
                    series.on_time += 1
                    b.on_time += 1
                else:
                    series.late += 1
                    b.late += 1
            if latency_ms is not None:
                b.bins[bin_i] += 1
                b.count += 1
                b.sum_ms += latency_ms
                if latency_ms > b.max_ms:
                    b.max_ms = latency_ms
                b.rows += hit_rows
                series.hit_rows += hit_rows
                series.deliveries += 1
                if stages is not None:
                    sm = b.stage_ms
                    lm = series.stage_ms
                    for i in range(_N_STAGES):
                        sm[i] += stages[i]
                        lm[i] += stages[i]
                if trace_id:
                    self._exemplar_locked(b, latency_ms, trace_id, now)
            self.observe_count += 1
        if latency_ms is not None:
            # late or slow deliveries burn the topic's delivery SLO
            # (engine lock is its own leaf — acquired after ours released)
            self.slo.observe("stream.delivery", on_time is not False,
                             latency_ms=latency_ms, key=topic)

    def note_dropped(self, topic: str, rows: int, chunks: int = 1) -> None:
        """A poisoned chunk's rows: never evaluated for ANY subscription
        of the topic — the ``dropped`` leg of on-time/late/dropped."""
        with self._lock:
            st = self._topic_locked(topic)
            st.dropped_rows += int(rows)
            st.dropped_chunks += int(chunks)

    def note_matrix(self, topic: str, *, capacity: int, active: int,
                    epoch: int, slot_bytes: int,
                    now: float | None = None) -> None:
        """One per-chunk capacity observation (occupancy / churn /
        growth trend source for the scale report)."""
        if now is None:
            now = self._clock()
        with self._lock:
            st = self._topic_locked(topic)
            st.slot_bytes = int(slot_bytes)
            r = st.ring
            if r and r[-1][1] == epoch and r[-1][2] == active:
                r[-1] = (r[-1][0], epoch, active, capacity)
                return
            r.append((now, epoch, active, capacity))

    # -- maintenance ----------------------------------------------------------
    def forget(self, topic: str) -> None:
        """Purge every series and the capacity state for ``topic`` (hub
        closed / topic retired)."""
        with self._lock:
            for key in [k for k in self._series if k[0] == topic]:
                del self._series[key]
            self._topics.pop(topic, None)
        self.slo.forget("stream.delivery", topic)

    # -- read surfaces --------------------------------------------------------
    def cost_rank(self, topic: str) -> list:
        """``[(subscription, lifetime_cost), ...]`` most expensive first —
        the valve ranking the watermark gauges share
        (stream/telemetry.py)."""
        with self._lock:
            rows = [(k[1], s.cost) for k, s in self._series.items()
                    if k[0] == topic]
        rows.sort(key=lambda r: (-r[1], r[0]))
        return rows

    def window_stats(self, topic: str, subscription, start_s: float,
                     end_s: float) -> dict:
        """Merged delivery stats over ``[start_s, end_s)``: the shared
        histogram quantiles plus hit rows / chunks / on-time / late /
        cost / per-stage ms."""
        extra = {"rows": 0, "chunks": 0, "on_time": 0, "late": 0,
                 "cost": 0.0}
        stage_ms = [0.0] * _N_STAGES

        def fold(b):
            extra["rows"] += b.rows
            extra["chunks"] += b.dispatches
            extra["on_time"] += b.on_time
            extra["late"] += b.late
            extra["cost"] += b.cost
            for i in range(_N_STAGES):
                stage_ms[i] += b.stage_ms[i]

        with self._lock:
            bins, count, sum_ms, max_ms = self._window_locked(
                (topic, str(subscription)), start_s, end_s, fold)
        judged = extra["on_time"] + extra["late"]
        return {
            "count": count,
            "sum_ms": sum_ms,
            "mean_ms": sum_ms / count if count else 0.0,
            "p50_ms": _quantile(bins, count, 0.5),
            "p95_ms": _quantile(bins, count, 0.95),
            "p99_ms": _quantile(bins, count, 0.99),
            "max_ms": max_ms,
            "hit_rows": extra["rows"],
            "chunks": extra["chunks"],
            "on_time": extra["on_time"],
            "late": extra["late"],
            "on_time_fraction": (extra["on_time"] / judged if judged
                                 else None),
            "cost": extra["cost"],
            "stage_ms": {STAGES[i]: round(stage_ms[i], 3)
                         for i in range(_N_STAGES)},
        }

    def exemplars(self, topic: str, subscription, limit: int = 16) -> list:
        """The series' retained chunk-trace exemplars, slowest first —
        each ``trace_id`` resolves via ``trace.find_trace`` to the
        stitched poll → cut → stage → scan → deliver span tree."""
        with self._lock:
            rows = self._exemplar_rows_locked((topic, str(subscription)))
        rows.sort(key=lambda r: -r["latency_ms"])
        return rows[:limit]

    def _capacity_section(self, st: _TopicState, now: float) -> dict:
        """Occupancy, churn, the predicted next bucket-crossing
        recompile, and the 1M-subscription HBM extrapolation — computed
        from the note_matrix ring (caller holds the lock)."""
        ring = list(st.ring)
        if not ring:
            return {"observed": False}
        t0, e0, a0, _c0 = ring[0]
        t1, e1, a1, cap = ring[-1]
        dt = max(t1 - t0, 0.0)
        churn = (e1 - e0) / dt if dt > 0 else 0.0  # epoch advances / s
        grow = (a1 - a0) / dt if dt > 0 else 0.0  # net subscriptions / s
        headroom = cap - a1  # adds until the power-of-two bucket crosses
        eta_s = headroom / grow if grow > 0 else None
        return {
            "observed": True,
            "capacity": cap,
            "active": a1,
            "occupancy": round(a1 / cap, 4) if cap else 0.0,
            "epoch": e1,
            "churn_per_s": round(churn, 4),
            "growth_per_s": round(grow, 4),
            "next_bucket_crossing": {
                # crossing capacity compiles the next (cached, per-bucket)
                # executable — the one planned recompile left on this path
                "adds_until_grow": headroom + 1,
                "eta_s": round(eta_s, 1) if eta_s is not None else None,
            },
            "hbm_bytes_per_subscription": st.slot_bytes,
            "hbm_bytes_at_1m": st.slot_bytes * 1_000_000,
            "dropped_rows": st.dropped_rows,
            "dropped_chunks": st.dropped_chunks,
        }

    def report(self, window_s: float = 300.0, limit: int = 50,
               topic: str | None = None) -> dict:
        """The standing-query scale report (``GET /api/obs/stream``,
        ``geomesa-tpu obs stream-report``): per topic, subscriptions
        ranked by lifetime scan-cost SHARE (delivery p99 alongside), the
        capacity section, and the valve's ``other`` rollup."""
        now = self._clock()
        with self._lock:
            keys = [k for k in self._series
                    if topic is None or k[0] == topic]
            keyset = set(keys)
            lifetime = {k: {"cost": s.cost, "hit_rows": s.hit_rows,
                            "deliveries": s.deliveries, "chunks": s.chunks,
                            "on_time": s.on_time, "late": s.late}
                        for k, s in self._series.items() if k in keyset}
            topics = {t: (self._capacity_section(st, now),
                          dict(st.other))
                      for t, st in self._topics.items()
                      if topic is None or t == topic}
        by_topic: dict[str, list] = {}
        for t, sub in keys:
            by_topic.setdefault(t, []).append(sub)
        out_topics = []
        for t in sorted(set(by_topic) | set(topics)):
            subs = by_topic.get(t, [])
            total_cost = sum(lifetime[(t, s)]["cost"] for s in subs)
            cap, other = topics.get(t, ({"observed": False}, None))
            if other:
                total_cost += other["cost"]
            entries = []
            for s in subs:
                life = lifetime[(t, s)]
                win = self.window_stats(t, s, now - window_s, now + 1.0)
                entries.append({
                    "subscription": s,
                    "cost": round(life["cost"], 3),
                    "cost_share": (round(life["cost"] / total_cost, 4)
                                   if total_cost else 0.0),
                    "hit_rows": life["hit_rows"],
                    "deliveries": life["deliveries"],
                    "chunks": life["chunks"],
                    "on_time": life["on_time"],
                    "late": life["late"],
                    "window": {k: (round(v, 3) if isinstance(v, float)
                                   else v)
                               for k, v in win.items()},
                    "exemplars": self.exemplars(t, s, limit=4),
                })
            entries.sort(key=lambda e: (-e["cost"], -e["window"]["p99_ms"]))
            out_topics.append({
                "topic": t,
                "subscriptions": entries[:limit],
                "series": len(subs),
                "capacity": cap,
                "other": other if (other and other["series"]) else None,
            })
        return {
            "topics": out_topics,
            "window_s": window_s,
            "bucket_s": self.bucket_s,
            "observe_count": self.observe_count,
            "slo": self.slo.snapshot(),
        }

    # -- prometheus exposition ------------------------------------------------
    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        """The ``geomesa_stream_delivery_*`` families: a TRUE histogram
        (``_ms_bucket``/``_sum``/``_count``) plus on-time / late / hit-row
        / cost counters per (topic, subscription), bounded at
        :data:`TOP_K` series per topic by cost with an ``other`` rollup
        row — and the lens's own ``stream.delivery`` SLO gauges under the
        ``{prefix}_stream`` prefix (distinct names: the store engine
        already emits ``{prefix}_slo_*``)."""
        with self._lock:
            per_topic: dict[str, list] = {}
            for (t, sub), s in self._series.items():
                bins = [0] * _N_BINS
                count = 0
                sum_ms = 0.0
                for b in s.buckets:
                    for i, c in enumerate(b.bins):
                        bins[i] += c
                    count += b.count
                    sum_ms += b.sum_ms
                per_topic.setdefault(t, []).append(
                    (sub, s.cost, bins, count, sum_ms, s.hit_rows,
                     s.on_time, s.late))
            others = {t: dict(st.other) for t, st in self._topics.items()}
            dropped = {t: st.dropped_rows for t, st in self._topics.items()}
        rows = []
        for t in sorted(per_topic):
            ranked = sorted(per_topic[t], key=lambda r: (-r[1], r[0]))
            spill = ranked[TOP_K:]
            for sub, cost, bins, count, sum_ms, hits, on, late in \
                    ranked[:TOP_K]:
                rows.append((t, sub, cost, bins, count, sum_ms, hits, on,
                             late))
            o = dict(others.get(t) or
                     {"series": 0, "cost": 0.0, "hit_rows": 0,
                      "deliveries": 0, "on_time": 0, "late": 0})
            obins = [0] * _N_BINS
            ocount = 0
            osum = 0.0
            for sub, cost, bins, count, sum_ms, hits, on, late in spill:
                o["series"] += 1
                o["cost"] += cost
                o["hit_rows"] += hits
                o["on_time"] += on
                o["late"] += late
                for i, c in enumerate(bins):
                    obins[i] += c
                ocount += count
                osum += sum_ms
            if o["series"]:
                rows.append((t, "other", o["cost"], obins, ocount, osum,
                             o["hit_rows"], o["on_time"], o["late"]))
        if not rows and not dropped:
            return []
        name = f"{prefix}_stream_delivery_ms"
        hist = [f"# TYPE {name} histogram"]
        on_l = [f"# TYPE {prefix}_stream_delivery_on_time_total counter"]
        late_l = [f"# TYPE {prefix}_stream_delivery_late_total counter"]
        hit_l = [f"# TYPE {prefix}_stream_delivery_hit_rows_total counter"]
        cost_l = [f"# TYPE {prefix}_stream_delivery_cost_units_total counter"]
        for t, sub, cost, bins, count, sum_ms, hits, on, late in rows:
            labels = f'topic="{_esc(t)}",subscription="{_esc(sub)}"'
            cum = 0
            for i, edge in enumerate(BUCKET_EDGES_MS):
                cum += bins[i]
                hist.append(
                    f'{name}_bucket{{{labels},le="{_fmt_le(edge)}"}} {cum}')
            hist.append(f'{name}_bucket{{{labels},le="+Inf"}} {count}')
            hist.append(f"{name}_sum{{{labels}}} {sum_ms:.6g}")
            hist.append(f"{name}_count{{{labels}}} {count}")
            on_l.append(
                f"{prefix}_stream_delivery_on_time_total{{{labels}}} {on}")
            late_l.append(
                f"{prefix}_stream_delivery_late_total{{{labels}}} {late}")
            hit_l.append(
                f"{prefix}_stream_delivery_hit_rows_total{{{labels}}} {hits}")
            cost_l.append(
                f"{prefix}_stream_delivery_cost_units_total{{{labels}}} "
                f"{cost:.6g}")
        drop_l = [f"# TYPE {prefix}_stream_delivery_dropped_rows_total "
                  "counter"]
        for t in sorted(dropped):
            drop_l.append(
                f'{prefix}_stream_delivery_dropped_rows_total'
                f'{{topic="{_esc(t)}"}} {dropped[t]}')
        out = hist + on_l + late_l + hit_l + cost_l + drop_l
        out += self.slo.prometheus_lines(prefix=f"{prefix}_stream")
        return out

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        lines = self.prometheus_lines(prefix)
        return "\n".join(lines) + "\n" if lines else ""


# -- backlog/freshness sentinel ----------------------------------------------

@shadow_plane
class BacklogSentinel:
    """Background backlog comparator (the ISSUE-17 sentinel shape:
    ``start()``/``close()`` around a daemon worker, ``evaluate_once()``
    for tests and the CLI).

    Per evaluation, per topic feeding the stream lens: watermark
    freshness (from the stream telemetry table — only meaningful while
    the scanner is actually behind, so freshness alone fires only with a
    nonzero queue), scanner queue depth, and the topic's
    ``stream.delivery`` burn rate. ``sustain`` consecutive burning
    evaluations latch ONE ``A_BACKLOG`` flight anomaly per episode (the
    recorder's dump rate-limit rides along) and the
    ``geomesa_stream_backlog`` gauge until the topic recovers.

    Evaluations run in audit shadow: sentinel reads must never meter a
    tenant or feed back into the lens."""

    def __init__(self, lens: StreamLens | None = None,
                 interval_s: float = 15.0, freshness_ms: float = 30_000.0,
                 max_scan_lag: int = 1_000_000, burn_factor: float = 2.0,
                 burn_window_s: float = 300.0, sustain: int = 1,
                 clock=time.time):
        self._lens = lens
        self.interval_s = interval_s
        self.freshness_ms = freshness_ms
        self.max_scan_lag = max_scan_lag
        self.burn_factor = burn_factor
        self.burn_window_s = burn_window_s
        self.sustain = max(1, sustain)
        self._clock = clock
        self._lock = threading.Lock()  # leaf: streaks + alarms
        self._streaks: dict[str, int] = {}
        self._alarms: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.eval_count = 0
        self.backlogs_total = 0

    @property
    def lens(self) -> StreamLens:
        return self._lens if self._lens is not None else get()

    # -- evaluation -----------------------------------------------------------
    def evaluate_once(self, now: float | None = None) -> list[dict]:
        """One comparator pass; returns the alarms RAISED this pass (an
        already-latched topic does not re-raise). Wraps itself in audit
        shadow."""
        from geomesa_tpu.obs import audit as _audit

        with _audit.shadow():
            return self._evaluate(self._clock() if now is None else now)

    def _evaluate(self, now: float) -> list[dict]:
        from geomesa_tpu.stream import telemetry as _telemetry

        lens = self.lens
        stream = _telemetry.report(now_ms=now * 1000.0)
        topics = set(stream) | {k[0] for k in lens.series_keys()}
        raised = []
        for t in sorted(topics):
            st = stream.get(t, {})
            scan_lag = int(st.get("scan_lag", 0))
            bus_lag = int(st.get("lag", 0))
            fresh = max(
                (wm["freshness_ms"]
                 for wm in (st.get("watermarks") or {}).values()),
                default=0.0,
            )
            causes = []
            if fresh > self.freshness_ms and (scan_lag > 0 or bus_lag > 0):
                causes.append(("freshness", fresh, self.freshness_ms))
            if scan_lag > self.max_scan_lag:
                causes.append(("queue_depth", float(scan_lag),
                               float(self.max_scan_lag)))
            burn = lens.slo.tracker("stream.delivery", t).burn_rate(
                self.burn_window_s)
            if burn >= self.burn_factor:
                causes.append(("slo_burn", burn, self.burn_factor))
            if not causes:
                with self._lock:
                    self._streaks.pop(t, None)
                    self._alarms.pop(t, None)
                continue
            with self._lock:
                streak = self._streaks.get(t, 0) + 1
                self._streaks[t] = streak
                fire = streak >= self.sustain and t not in self._alarms
                if fire:
                    kind, live_v, limit_v = causes[0]
                    alarm = {
                        "topic": t, "cause": kind,
                        "value": round(live_v, 3),
                        "threshold": round(limit_v, 3),
                        "scan_lag": scan_lag, "lag": bus_lag,
                        "freshness_ms": round(fresh, 1),
                        "burn_rate": round(burn, 3), "ts": now,
                    }
                    self._alarms[t] = alarm
                    self.backlogs_total += 1
            if fire:
                raised.append(alarm)
                self._raise_anomaly(alarm)
        with self._lock:
            self.eval_count += 1
        return raised

    def _raise_anomaly(self, alarm: dict) -> None:
        # one A_BACKLOG flight record per episode (the recorder's dump
        # throttle bounds file output under a storm). flight.record is
        # the operator surface — an alert raised from shadow is the point.
        from geomesa_tpu.obs import flight as _flight

        _flight.record(
            "stream.sentinel", alarm["topic"], source="sentinel",
            plan=(f"{alarm['cause']}: {alarm['value']:.6g} over "
                  f"{alarm['threshold']:.6g} (scan_lag={alarm['scan_lag']}, "
                  f"freshness={alarm['freshness_ms']:.6g} ms, "
                  f"burn={alarm['burn_rate']:.3g})"),
            latency_ms=alarm["freshness_ms"],
            plan_signature="stream.delivery",
            anomalies=(_flight.A_BACKLOG,),
        )

    # -- worker ---------------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="geomesa-backlog-sentinel",
                daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # pragma: no cover — the sentinel must not die
                pass

    # -- read surfaces --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "alarms": list(self._alarms.values()),
                "eval_count": self.eval_count,
                "backlogs_total": self.backlogs_total,
                "freshness_ms": self.freshness_ms,
                "max_scan_lag": self.max_scan_lag,
                "burn_factor": self.burn_factor,
                "running": self._thread is not None,
            }

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        with self._lock:
            alarms = list(self._alarms.values())
            total = self.backlogs_total
        out = [f"# TYPE {prefix}_stream_backlog gauge"]
        for a in alarms:
            out.append(
                f'{prefix}_stream_backlog{{topic="{_esc(a["topic"])}",'
                f'cause="{_esc(a["cause"])}"}} 1')
        out.append(f"# TYPE {prefix}_stream_backlogs_total counter")
        out.append(f"{prefix}_stream_backlogs_total {total}")
        return out

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        return "\n".join(self.prometheus_lines(prefix)) + "\n"


# process-wide singletons (tests swap with install()/install_sentinel())
_lens = StreamLens()
_sentinel = BacklogSentinel()


def get() -> StreamLens:
    """The process-wide stream lens."""
    return _lens


def install(lens: StreamLens) -> StreamLens:
    """Swap the process stream lens (tests); returns the previous one."""
    global _lens
    prev, _lens = _lens, lens
    return prev


def sentinel() -> BacklogSentinel:
    """The process-wide backlog sentinel (not started by default;
    servers opt in via ``start()``)."""
    return _sentinel


def install_sentinel(s: BacklogSentinel) -> BacklogSentinel:
    """Swap the process sentinel (tests); returns the previous one —
    callers own closing the outgoing worker."""
    global _sentinel
    prev, _sentinel = _sentinel, s
    return prev
