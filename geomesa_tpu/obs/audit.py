"""Continuous correctness auditor: sampled shadow re-execution against an
independent referee, background invariant sweeps, and auto-captured
minimized repro bundles (docs/observability.md § Continuous correctness
auditing).

The platform's fast paths — device refine kernels, the exec-cache
memoized select, the cheap-select route, the GeoBlocks pyramid + query
cache, coalesced ``select_many`` batches, sharded fan-out — are parity-
asserted in bench legs on synthetic data, never against live traffic.
This module observes their correctness continuously, the way the obs
stack already observes latency, devices and tenants:

1. **Sampled shadow re-execution.** ``GEOMESA_TPU_AUDIT`` (a [0,1]
   rate) or ``hints={"audit": True}`` tags completed queries; their
   (filter, hints, auths, data-epoch) plus the LIVE answer are enqueued
   to a bounded low-priority worker that re-executes them on the
   independent referee path (:mod:`geomesa_tpu.ops.referee`: host-side
   f64 NumPy scan over the base snapshot — no Z-decomposition, no
   device kernels, no pyramid/cache/memo) and compares fid-set equality
   for selects, exact counts, and f64-tolerance grouped-agg values.
   When the live data epoch ``(rebuild_epoch, delta.version)`` has
   moved past the captured one the check ABSTAINS — counted, never
   alarming — so concurrent writes can only cost coverage, not produce
   a false alarm.

2. **Background invariant sweeps** (:class:`InvariantSweeper`):
   structural invariants shadow queries cannot see — pyramid partials
   reconcile against base per (bin, cell) on a rotating cell sample,
   devmon ledger vs ``TpuBackend.residency()`` agreement, query-cache
   entry epochs never ahead of the live epoch (and never outliving
   their schema), sharded-view Z-domain coverage disjoint and total,
   subscription-matrix unsat-sentinel slots matching nothing, and a
   standing query's cumulative delivered count cross-checked against
   ``DataStore.query`` at the same epoch.

3. **Divergence handling.** A confirmed mismatch becomes a typed
   :class:`DivergenceReport`: an ``A_DIVERGE`` flight anomaly,
   ``geomesa_audit_*`` prometheus counters (checked/passed/diverged/
   abstained per check kind), and a **repro bundle** under
   ``GEOMESA_TPU_AUDIT_DIR`` — the ISSUE-11-shaped workload event plus
   epoch, both answers, and a delta-debugged MINIMIZED predicate
   (conjuncts dropped / ranges halved while the divergence persists) —
   replayable via ``geomesa-tpu replay --bundle``.

Hygiene: every execution the auditor itself triggers (referee scans are
pure host code; the minimizer ALSO re-runs the live path) runs inside
:func:`shadow`, and the store's feedback planes — CostTable
observations, usage metering, SLO burn, workload capture — all consult
:func:`in_shadow` and skip shadow traffic (the same rule ISSUE 11's
replay applies to capture). The off path costs one module-global bool
plus one ContextVar read per query (<2% bound gated in scripts/lint.sh).

Locking (docs/concurrency.md): the auditor lock and the sweeper lock
are LEAVES guarding queue/counters/verdicts only; referee execution,
store snapshots, minimization and file I/O all run outside them. No jax
anywhere (``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import asdict, dataclass, field

from geomesa_tpu.analysis.contracts import shadow_guard, shadow_plane

__all__ = [
    "AUDIT_DIR_ENV", "AUDIT_ENV", "ContinuousAuditor", "DivergenceReport",
    "InvariantSweeper", "enabled", "get", "in_shadow", "install",
    "minimize_predicate", "replay_bundle", "sampled", "shadow",
]

AUDIT_ENV = "GEOMESA_TPU_AUDIT"
AUDIT_DIR_ENV = "GEOMESA_TPU_AUDIT_DIR"

# hints that reshape the result into something the select referee cannot
# compare fid-for-fid (grids, sketches, byte streams, row subsets)
_INELIGIBLE_HINTS = ("density", "stats", "bin", "sample", "sample_by",
                    "knn")

_CHECK_KINDS = ("select", "count", "agg")


def _env_rate() -> float:
    raw = os.environ.get(AUDIT_ENV, "").strip()
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        raise ValueError(
            f"{AUDIT_ENV} must be a sampling rate in [0, 1], got {raw!r}"
        ) from None
    return min(max(rate, 0.0), 1.0)


# THE one check the per-query hot path pays when auditing is off
# (module-global bool, same pattern as workload.ENABLED)
_rate = _env_rate()
ENABLED = _rate > 0.0
_sample_acc = 0.0


def enabled() -> bool:
    return ENABLED


def set_rate(rate: float) -> None:
    """Set the sampling rate (tests / install); 0 disables the env path
    (per-query ``hints={"audit": True}`` still audits)."""
    global _rate, ENABLED, _sample_acc
    _rate = min(max(float(rate), 0.0), 1.0)
    ENABLED = _rate > 0.0
    _sample_acc = 0.0


def sampled() -> bool:
    """Deterministic rate-accumulator sampling: at rate r, ~every 1/r-th
    completed query audits (rate 1.0 = every query). Racy increments
    under free threading can only LOSE ticks — sampling, not accounting."""
    global _sample_acc
    if _rate <= 0.0:
        return False
    _sample_acc += _rate
    if _sample_acc >= 1.0:
        _sample_acc -= 1.0
        return True
    return False


# -- shadow mode --------------------------------------------------------------
# ContextVar (not threading.local): it crosses into the watchdog's
# copy_context worker threads the same way trace spans do, so a shadow
# re-execution stays shadow end to end.
_shadow_var: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "geomesa_audit_shadow", default=False)


@shadow_guard
def in_shadow() -> bool:
    """True inside an auditor-triggered execution: the store's feedback
    planes (cost table, usage metering, SLO burn, workload capture)
    consult this and skip — audit traffic must not train the planner,
    bill a tenant, burn an SLO budget, or recapture itself."""
    return _shadow_var.get()


@shadow_guard
@contextmanager
def shadow():
    token = _shadow_var.set(True)
    try:
        yield
    finally:
        _shadow_var.reset(token)


def eligible_select(q) -> bool:
    """Can this query's answer be compared fid-for-fid against the
    referee? Paging/limits make the row subset plan-dependent, and
    aggregation/sampling hints reshape the result entirely."""
    if q.limit is not None or q.start_index is not None:
        return False
    return not any(k in q.hints for k in _INELIGIBLE_HINTS)


def eligible_agg(q) -> bool:
    return (q.limit is None and q.start_index is None
            and not any(k in q.hints for k in _INELIGIBLE_HINTS))


def filter_text(q) -> str:
    f = q.filter
    if f is None:
        return "INCLUDE"
    if isinstance(f, str):
        return f
    from geomesa_tpu.filter import ast as _ast

    try:
        return _ast.to_cql(f)
    except ValueError:
        return str(f)


# -- divergence reports -------------------------------------------------------

@dataclass
class DivergenceReport:
    """One confirmed live-vs-referee mismatch (or invariant violation)."""

    ts: float
    kind: str  # "select" | "count" | "agg" | "sweep:<check>"
    type_name: str
    filter_text: str
    epoch: tuple | None
    detail: str  # human-readable mismatch description
    minimized: str = ""  # delta-debugged predicate (query checks only)
    live_summary: str = ""
    referee_summary: str = ""
    bundle_path: str | None = None
    tenant: str = ""


# -- predicate minimization ---------------------------------------------------

def _narrowings(node):
    """Narrowed variants of one leaf: halved spatial boxes / time windows."""
    from dataclasses import replace as _replace

    from geomesa_tpu.filter import ast as _ast

    if isinstance(node, _ast.BBox) and node.xmin <= node.xmax:
        xm = (node.xmin + node.xmax) / 2.0
        ym = (node.ymin + node.ymax) / 2.0
        if node.xmax - node.xmin > 1e-9:
            yield _replace(node, xmax=xm)
            yield _replace(node, xmin=xm)
        if node.ymax - node.ymin > 1e-9:
            yield _replace(node, ymax=ym)
            yield _replace(node, ymin=ym)
    elif isinstance(node, _ast.During):
        if node.hi_millis - node.lo_millis > 2:
            mid = (node.lo_millis + node.hi_millis) // 2
            yield _replace(node, hi_millis=mid)
            yield _replace(node, lo_millis=mid)


def _rebuild(node, target, new):
    """``node`` with ``target`` (identity) replaced by ``new``."""
    from geomesa_tpu.filter import ast as _ast

    if node is target:
        return new
    if isinstance(node, _ast.And):
        return _ast.And(tuple(_rebuild(c, target, new)
                              for c in node.children))
    if isinstance(node, _ast.Or):
        return _ast.Or(tuple(_rebuild(c, target, new)
                             for c in node.children))
    if isinstance(node, _ast.Not):
        return _ast.Not(_rebuild(node.child, target, new))
    return node


def _leaves(node):
    from geomesa_tpu.filter import ast as _ast

    if isinstance(node, (_ast.And, _ast.Or)):
        for c in node.children:
            yield from _leaves(c)
    elif isinstance(node, _ast.Not):
        yield from _leaves(node.child)
    else:
        yield node


def minimize_predicate(f, diverges, max_checks: int = 48):
    """Delta-debug one diverging predicate: drop conjuncts and halve
    box/window ranges while ``diverges(candidate)`` stays True, bounded
    at ``max_checks`` re-executions. ``diverges`` must return False for
    candidates it cannot verify (epoch moved, execution error) — the
    minimizer then simply keeps the larger predicate, so a racing write
    can stall minimization but never yield a non-reproducing bundle."""
    from geomesa_tpu.filter import ast as _ast

    budget = [max_checks]

    def still(cand) -> bool:
        if budget[0] <= 0:
            return False
        budget[0] -= 1
        try:
            return bool(diverges(cand))
        except Exception:  # noqa: BLE001 — an unverifiable candidate is kept out
            return False

    cur = f
    changed = True
    while changed and budget[0] > 0:
        changed = False
        # 1-minimal conjunct drop (ddmin over the top-level AND)
        if isinstance(cur, _ast.And) and len(cur.children) > 1:
            for i in range(len(cur.children)):
                rest = cur.children[:i] + cur.children[i + 1:]
                cand = rest[0] if len(rest) == 1 else _ast.And(rest)
                if still(cand):
                    cur = cand
                    changed = True
                    break
            if changed:
                continue
        # range halving on the surviving leaves
        for leaf in list(_leaves(cur)):
            for narrowed in _narrowings(leaf):
                cand = _rebuild(cur, leaf, narrowed)
                if still(cand):
                    cur = cand
                    changed = True
                    break
            if changed:
                break
    return cur


# -- the auditor --------------------------------------------------------------

class _Check:
    __slots__ = ("store_ref", "type_name", "kind", "q", "epoch", "live",
                 "group_by", "value_cols", "cutoff_ms", "tenant", "ts")

    def __init__(self, store, type_name, kind, q, epoch, live,
                 group_by=None, value_cols=(), cutoff_ms=None,
                 tenant=""):
        self.store_ref = weakref.ref(store)
        self.type_name = type_name
        self.kind = kind
        self.q = q
        self.epoch = epoch
        self.live = live
        self.group_by = group_by
        self.value_cols = tuple(value_cols or ())
        self.cutoff_ms = cutoff_ms
        self.tenant = tenant
        self.ts = time.time()


@shadow_plane
class ContinuousAuditor:
    """Bounded low-priority shadow-re-execution worker.

    ``enqueue_*`` is the hot-path side: build a check item, append under
    the leaf lock, drop-and-count when the queue is full (audit coverage
    degrades before the serving path does). The worker thread (lazily
    started; deterministic idempotent ``close``) pops one item at a
    time and runs the referee comparison OUTSIDE the lock. ``drain()``
    runs every queued check on the calling thread — the synchronous
    surface tests and ``explain(analyze=True)`` use."""

    def __init__(self, rate: float | None = None,
                 bundle_dir: str | None = None,
                 max_queue: int = 256, minimize_steps: int = 48,
                 autostart: bool = True, clock=time.time):
        if rate is not None:
            set_rate(rate)
        # the rate THIS auditor runs at: install() re-applies it, so a
        # swap-back (install(prev)) restores the previous sampling rate
        # instead of leaving the swapped-in auditor's rate behind
        self.rate = rate if rate is not None else _rate
        if bundle_dir is None:
            bundle_dir = os.environ.get(AUDIT_DIR_ENV) or None
        self.bundle_dir = bundle_dir
        self.max_queue = max_queue
        self.minimize_steps = minimize_steps
        self.autostart = autostart
        self._clock = clock
        self._lock = threading.Lock()  # leaf: queue + counters + verdicts
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # per-kind counters (the geomesa_audit_* series)
        self.checked: dict[str, int] = {}
        self.passed: dict[str, int] = {}
        self.diverged: dict[str, int] = {}
        self.abstained: dict[str, int] = {}
        self.dropped = 0  # queue-full drops
        self.errors = 0  # referee execution errors (counted, never raised)
        self.bundles_written = 0
        self.divergences: deque = deque(maxlen=64)
        self._sweeps: dict[str, dict] = {}  # last result per sweep check
        # (type, filter text) -> verdict dict, for explain's Audit: line
        self._verdicts: OrderedDict = OrderedDict()

    # -- hot-path side --------------------------------------------------------
    def enqueue_select(self, store, type_name: str, q, epoch,
                       table) -> bool:
        fids = tuple(str(f) for f in table.fids)
        return self._enqueue(_Check(store, type_name, "select", q, epoch,
                                    fids, tenant=self._tenant(q)))

    def enqueue_count(self, store, type_name: str, q, epoch,
                      count: int) -> bool:
        return self._enqueue(_Check(store, type_name, "count", q, epoch,
                                    int(count), tenant=self._tenant(q)))

    def enqueue_agg(self, store, type_name: str, q, epoch, result,
                    group_by, value_cols, cutoff_ms=None) -> bool:
        from geomesa_tpu.ops.referee import live_agg_map

        live = live_agg_map(result, list(value_cols or ()))
        return self._enqueue(_Check(
            store, type_name, "agg", q, epoch, live, group_by=group_by,
            value_cols=value_cols, cutoff_ms=cutoff_ms,
            tenant=self._tenant(q)))

    @staticmethod
    def _tenant(q) -> str:
        from geomesa_tpu.obs import usage as _usage

        return q.hints.get("tenant") or _usage.current_tenant() or ""

    def _enqueue(self, item: _Check) -> bool:
        start = False
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self.dropped += 1
                return False
            self._queue.append(item)
            self._cv.notify()
            if (self.autostart and self._thread is None
                    and not self._stop.is_set()):
                start = True
                self._thread = threading.Thread(
                    target=self._run, name="geomesa-audit", daemon=True)
        if start:
            self._thread.start()
        return True

    # -- worker side ----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    # CV wait releases the lock (worker parks when idle)
                    # tpurace: disable-next-line=R003
                    self._cv.wait(0.25)
                if self._stop.is_set():
                    return
                # _cv is Condition(self._lock): the auditor lock IS held
                # here — the lockset analyzer can't see through Condition
                # tpulint: disable-next-line=R001
                item = self._queue.popleft()
            self._execute(item)

    def drain(self) -> int:
        """Run every queued check on the calling thread; returns the
        number executed (tests / explain / CLI)."""
        n = 0
        while True:
            with self._lock:
                if not self._queue:
                    return n
                item = self._queue.popleft()
            self._execute(item)
            n += 1

    def close(self) -> None:
        """Deterministic idempotent shutdown of the worker thread."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- check execution ------------------------------------------------------
    def _count(self, table: dict, kind: str) -> None:
        table[kind] = table.get(kind, 0) + 1

    def _note_verdict(self, item: _Check, verdict: str, detail: str = ""):
        key = (item.type_name, filter_text(item.q))
        with self._lock:
            self._verdicts[key] = {
                "verdict": verdict, "kind": item.kind, "detail": detail,
                "ts": self._clock(),
            }
            self._verdicts.move_to_end(key)
            while len(self._verdicts) > 128:
                self._verdicts.popitem(last=False)

    def last_verdict(self, type_name: str, text: str | None = None):
        """The newest verdict for (type, filter) — or for the type alone
        when the exact text is absent (TTL stores rewrite the filter
        between the caller and the audit hook)."""
        with self._lock:
            if text is not None:
                hit = self._verdicts.get((type_name, text))
                if hit is not None:
                    return dict(hit)
            for (t, _txt), v in reversed(self._verdicts.items()):
                if t == type_name:
                    return dict(v)
        return None

    @staticmethod
    def _snapshot_at_epoch(store, type_name: str, epoch):
        """(sft, main, delta) when the live epoch still equals ``epoch``,
        else None (→ abstain). Epoch is re-read AFTER the snapshot:
        equality means no mutation landed in between, so the snapshot IS
        the captured-epoch data."""
        st = store._types.get(type_name)
        if st is None:
            return None
        main, _idx, _bs, _stats, delta = st.snapshot()
        if st.data_epoch() != tuple(epoch):
            return None
        return st.sft, main, delta

    def _execute(self, item: _Check) -> None:
        with self._lock:
            self._count(self.checked, item.kind)
        store = item.store_ref()
        if store is None:
            with self._lock:
                self._count(self.abstained, item.kind)
            return
        try:
            with shadow():
                self._execute_inner(store, item)
        except Exception:  # noqa: BLE001 — the auditor must never take down its host
            with self._lock:
                self.errors += 1

    def _execute_inner(self, store, item: _Check) -> None:
        from geomesa_tpu.ops import referee as _referee

        snap = self._snapshot_at_epoch(store, item.type_name, item.epoch)
        if snap is None:
            with self._lock:
                self._count(self.abstained, item.kind)
            self._note_verdict(item, "abstained", "epoch moved")
            return
        sft, main, delta = snap
        if item.kind == "agg":
            ref = _referee.referee_agg(
                sft, main, delta, item.q, item.group_by, item.value_cols,
                cutoff_ms=item.cutoff_ms)
            ok, detail = _referee.agg_equal(item.live, ref)
            live_s = f"{len(item.live)} groups"
            ref_s = f"{len(ref)} groups"
        else:
            ref_fids = _referee.referee_select(sft, main, delta, item.q)
            if item.kind == "count":
                ok = int(item.live) == len(ref_fids)
                detail = (f"count live={item.live} "
                          f"referee={len(ref_fids)}") if not ok else ""
                live_s = str(item.live)
                ref_s = str(len(ref_fids))
            else:
                # live fids arrive in result-table order; the referee
                # sorts — compare as multisets
                ok, detail = _referee.fid_sets_equal(
                    sorted(item.live), ref_fids)
                live_s = f"{len(item.live)} fids"
                ref_s = f"{len(ref_fids)} fids"
        if ok:
            with self._lock:
                self._count(self.passed, item.kind)
            self._note_verdict(item, "pass")
            return
        self._handle_divergence(store, item, detail, live_s, ref_s)

    # -- divergence path ------------------------------------------------------
    def _diverges_fn(self, store, item: _Check):
        """Predicate-level divergence oracle for the minimizer: re-run
        the LIVE path (in shadow — the feedback planes must not see it)
        and the referee with a candidate filter; True only when they
        still disagree AND the epoch held for both executions."""
        from dataclasses import replace as _replace

        from geomesa_tpu.ops import referee as _referee

        def diverges(cand) -> bool:
            q = _replace(item.q, filter=cand, hints={
                k: v for k, v in item.q.hints.items() if k != "audit"
            })
            if store._types.get(item.type_name) is None:
                return False
            st = store._types[item.type_name]
            if st.data_epoch() != tuple(item.epoch):
                return False
            # re-run the SAME live lane that produced the divergence: a
            # batched-count bug must be verified through count_many, not
            # through the (possibly correct) single-select path
            if item.kind == "agg":
                out = store.aggregate_many(
                    item.type_name, [q], group_by=item.group_by,
                    value_cols=item.value_cols)
                live_val = out[0]
            elif item.kind == "count":
                live_val = store.count_many(
                    item.type_name, [q], loose=False)[0]
            else:
                live_val = store.query(item.type_name, q)
            snap = self._snapshot_at_epoch(
                store, item.type_name, item.epoch)
            if snap is None:
                return False
            sft, main, delta = snap
            if item.kind == "agg":
                if live_val is None:
                    return False
                lm = _referee.live_agg_map(live_val, list(item.value_cols))
                ref = _referee.referee_agg(
                    sft, main, delta, q, item.group_by, item.value_cols,
                    cutoff_ms=item.cutoff_ms)
                return not _referee.agg_equal(lm, ref)[0]
            ref_fids = _referee.referee_select(sft, main, delta, q)
            if item.kind == "count":
                return int(live_val) != len(ref_fids)
            live_fids = sorted(str(f) for f in live_val.table.fids)
            return live_fids != ref_fids

        return diverges

    def _handle_divergence(self, store, item: _Check, detail: str,
                           live_s: str, ref_s: str) -> None:
        from geomesa_tpu.filter import ast as _ast
        from geomesa_tpu.obs import flight as _flight

        f = item.q.resolved_filter()
        minimized = f
        if not isinstance(f, _ast.Include) and self.minimize_steps > 0:
            minimized = minimize_predicate(
                f, self._diverges_fn(store, item),
                max_checks=self.minimize_steps)
        try:
            min_text = _ast.to_cql(minimized)
        except ValueError:
            min_text = str(minimized)
        report = DivergenceReport(
            ts=self._clock(), kind=item.kind, type_name=item.type_name,
            filter_text=filter_text(item.q), epoch=tuple(item.epoch),
            detail=detail, minimized=min_text,
            live_summary=live_s, referee_summary=ref_s,
            tenant=item.tenant,
        )
        report.bundle_path = self._write_bundle(item, report)
        with self._lock:
            self._count(self.diverged, item.kind)
            if report.bundle_path is not None:
                self.bundles_written += 1
            self.divergences.append(report)
        self._note_verdict(item, "diverged", detail)
        # A_DIVERGE flight anomaly: the record lands in the always-on
        # ring (and triggers a throttled Perfetto dump when a flight
        # dir is configured) so "what diverged and when" is answerable
        # from the flight surfaces alone
        _flight.record(
            op=f"audit.{item.kind}", type_name=item.type_name,
            source="audit", plan=report.filter_text,
            rows=0, anomalies=(_flight.A_DIVERGE,),
            tenant=item.tenant,
        )

    def _bundle_event(self, item: _Check) -> dict:
        """The ISSUE 11 workload wide-event shape for the diverging
        query — what ``geomesa-tpu replay --bundle`` re-issues."""
        from geomesa_tpu.obs.workload import _REPLAYABLE_HINTS, _json_safe

        return {
            "ts_arrival": round(item.ts, 6),
            "ts": round(item.ts, 6),
            "op": "query" if item.kind != "agg" else "aggregate",
            "type": item.type_name,
            "source": "audit",
            "filter": filter_text(item.q),
            "hints": {k: _json_safe(v) for k, v in item.q.hints.items()
                      if k in _REPLAYABLE_HINTS} or None,
            "tenant": item.tenant,
            "auths": (list(item.q.auths)
                      if item.q.auths is not None else None),
            "plan_signature": "", "predicted_ms": None,
            "latency_ms": 0.0, "rows": 0, "bytes_out": 0,
            "trace_id": "", "device_ms": 0.0, "degraded": False,
        }

    def _live_payload(self, item: _Check):
        if item.kind == "agg":
            return {str(k): v for k, v in item.live.items()}
        if item.kind == "count":
            return int(item.live)
        return list(item.live)

    def _write_bundle(self, item: _Check, report: DivergenceReport):
        if not self.bundle_dir:
            return None
        doc = {
            "kind": "geomesa-audit-repro-bundle",
            "version": 1,
            "check": item.kind,
            "event": self._bundle_event(item),
            "epoch": list(item.epoch),
            "group_by": list(item.group_by or []),
            "value_cols": list(item.value_cols),
            "cutoff_ms": item.cutoff_ms,
            "live": self._live_payload(item),
            "detail": report.detail,
            "minimized": report.minimized,
        }
        path = os.path.join(
            self.bundle_dir,
            f"repro-{int(report.ts * 1000)}-{item.kind}-"
            f"{self.bundles_written}.json")
        try:
            os.makedirs(self.bundle_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, default=str)
        except OSError:
            return None  # a full disk must not fail the audit path
        return path

    # -- external-plane feed (trajectory corridor/interlink shadow checks) ----
    def note_check(self, kind: str, ok: bool, type_name: str = "",
                   detail: str = "", abstain: bool = False) -> None:
        """Fold one externally-executed shadow comparison into the audit
        counters (``geomesa_audit_*{kind=...}``) — the trajectory plane's
        corridor/interlink engines compare their device results against
        the demoted host referees themselves (inside ``shadow()``) and
        report the verdict here; divergences raise the same ``A_DIVERGE``
        flight anomaly as query divergences."""
        with self._lock:
            self._count(self.checked, kind)
            if abstain:
                self._count(self.abstained, kind)
            elif ok:
                self._count(self.passed, kind)
            else:
                self._count(self.diverged, kind)
        if not ok and not abstain:
            from geomesa_tpu.obs import flight as _flight

            report = DivergenceReport(
                ts=self._clock(), kind=kind, type_name=type_name,
                filter_text="", epoch=None, detail=detail)
            with self._lock:
                self.divergences.append(report)
            _flight.record(
                op=kind, type_name=type_name, source="audit",
                plan=detail[:200], rows=0, anomalies=(_flight.A_DIVERGE,))

    # -- sweeper feed ---------------------------------------------------------
    def note_sweep(self, name: str, result: dict) -> None:
        kind = f"sweep:{name}"
        with self._lock:
            self._count(self.checked, kind)
            if result.get("abstained"):
                self._count(self.abstained, kind)
            elif result.get("violations"):
                self._count(self.diverged, kind)
            else:
                self._count(self.passed, kind)
            self._sweeps[name] = result
        if result.get("violations"):
            from geomesa_tpu.obs import flight as _flight

            report = DivergenceReport(
                ts=self._clock(), kind=kind,
                type_name=result.get("type_name", ""),
                filter_text="", epoch=None,
                detail="; ".join(str(v) for v in result["violations"][:4]),
            )
            with self._lock:
                self.divergences.append(report)
            _flight.record(
                op=kind, type_name=report.type_name, source="audit",
                plan=report.detail[:200], rows=0,
                anomalies=(_flight.A_DIVERGE,),
            )

    # -- read surface ---------------------------------------------------------
    def snapshot(self, limit: int = 32) -> dict:
        """The ``GET /api/obs/audit`` payload."""
        with self._lock:
            kinds = sorted(set(self.checked) | set(_CHECK_KINDS))
            counters = {
                k: {
                    "checked": self.checked.get(k, 0),
                    "passed": self.passed.get(k, 0),
                    "diverged": self.diverged.get(k, 0),
                    "abstained": self.abstained.get(k, 0),
                }
                for k in kinds
            }
            div = [asdict(d) for d in list(self.divergences)[-limit:]]
            sweeps = {k: dict(v) for k, v in self._sweeps.items()}
            out = {
                "rate": _rate,
                "enabled": ENABLED,
                "queue_depth": len(self._queue),
                "dropped": self.dropped,
                "errors": self.errors,
                "bundles_written": self.bundles_written,
                "bundle_dir": self.bundle_dir,
                "checks": counters,
                "divergences": div,
                "sweeps": sweeps,
            }
        return out

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        with self._lock:
            kinds = sorted(set(self.checked) | set(_CHECK_KINDS))
            tables = (("checked", self.checked), ("passed", self.passed),
                      ("diverged", self.diverged),
                      ("abstained", self.abstained))
            lines: list[str] = []
            for name, table in tables:
                metric = f"{prefix}_audit_{name}_total"
                lines.append(f"# TYPE {metric} counter")
                for k in kinds:
                    lines.append(f'{metric}{{kind="{k}"}} {table.get(k, 0)}')
            lines.append(f"# TYPE {prefix}_audit_dropped_total counter")
            lines.append(f"{prefix}_audit_dropped_total {self.dropped}")
            lines.append(f"# TYPE {prefix}_audit_bundles_total counter")
            lines.append(
                f"{prefix}_audit_bundles_total {self.bundles_written}")
        return lines

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        return "\n".join(self.prometheus_lines(prefix)) + "\n"


# -- invariant sweeps ---------------------------------------------------------

@shadow_plane
class InvariantSweeper:
    """Periodic validator of structural invariants shadow queries cannot
    see. Attach surfaces (``attach_store`` / ``attach_view`` /
    ``attach_stream`` / ``attach_matrix``), then either run
    :meth:`sweep_once` explicitly (tests, CLI) or :meth:`start` the
    background thread. Every check result feeds the auditor's
    ``sweep:<name>`` counters; violations raise ``A_DIVERGE`` flight
    anomalies through the same path as query divergences."""

    # (bin, cell, group) partials below this compare in ONE vectorized
    # recount per sweep (deterministic full coverage); above it the
    # rotating cell sample bounds per-sweep cost
    FULL_COMPARE_CELLS = 1 << 22

    def __init__(self, auditor: "ContinuousAuditor | None" = None,
                 interval_s: float = 10.0, cell_sample: int = 16):
        self._auditor = auditor
        self.interval_s = interval_s
        self.cell_sample = cell_sample
        self._lock = threading.Lock()  # leaf: target lists + cursors
        self._stores: list = []  # weakrefs to DataStore
        self._views: list = []  # weakrefs to ShardedDataStoreView
        self._streams: list = []  # weakrefs to streaming stores
        self._matrices: list = []  # weakrefs to SubscriptionMatrix
        self._tracks: list = []  # weakrefs to trajectory TrackState
        self._pools: list = []  # weakrefs to BufferPool (tier coherence)
        self._pyr_cursor = 0  # rotating cell-sample cursor
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.sweep_count = 0

    @property
    def auditor(self) -> ContinuousAuditor:
        return self._auditor if self._auditor is not None else get()

    def _attach(self, bucket: list, obj) -> None:
        with self._lock:
            bucket[:] = [r for r in bucket if r() is not None]
            if not any(r() is obj for r in bucket):
                bucket.append(weakref.ref(obj))

    def attach_store(self, store) -> None:
        self._attach(self._stores, store)

    def attach_view(self, view) -> None:
        self._attach(self._views, view)

    def attach_stream(self, store) -> None:
        self._attach(self._streams, store)

    def attach_matrix(self, matrix) -> None:
        self._attach(self._matrices, matrix)

    def attach_track_state(self, state) -> None:
        self._attach(self._tracks, state)

    def attach_pool(self, pool) -> None:
        self._attach(self._pools, pool)

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="geomesa-audit-sweeper", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep_once()
            except Exception:  # noqa: BLE001 — a sweep bug must not kill the thread
                pass

    def _targets(self, bucket: list) -> list:
        with self._lock:
            objs = [r() for r in bucket]
            bucket[:] = [r for r in bucket if r() is not None]
        return [o for o in objs if o is not None]

    def sweep_once(self) -> list[dict]:
        """One pass over every attached surface; returns the per-check
        results (also folded into the auditor counters). Runs in audit
        shadow: the standing-count check issues real ``store.query``
        calls, and sweep traffic must stay invisible to the feedback
        planes (and must never be sampled into fresh audit checks)."""
        out: list[dict] = []
        with shadow():
            for store in self._targets(self._stores):
                out.append(self.check_pyramids(store))
                out.append(self.check_ledger(store))
                out.append(self.check_query_cache(store))
                out.append(self.check_wal(store))
            for view in self._targets(self._views):
                out.append(self.check_shard_coverage(view))
            for m in self._targets(self._matrices):
                out.append(self.check_matrix_sentinels(m))
            for s in self._targets(self._streams):
                out.append(self.check_standing_counts(s))
            for ts in self._targets(self._tracks):
                out.append(self.check_track_state(ts))
            for pool in self._targets(self._pools):
                out.append(self.check_tiering(pool))
        for r in out:
            self.auditor.note_sweep(r["check"], r)
        with self._lock:
            self.sweep_count += 1
        return out

    # -- individual checks ----------------------------------------------------
    def check_wal(self, store) -> dict:
        """WAL/checkpoint invariants for durability-plane stores
        (docs/operations.md § Durability & recovery): every type's applied
        seq is at/below the WAL's seq high-water (an applied mutation the
        journal never issued a seq for cannot exist); each topic's trimmed
        head never passes its commit offset (a trim past the commit would
        have destroyed un-checkpointed records); and the manifest's replay
        floors never exceed the live applied seqs (a stamp ahead of the
        state would make recovery skip acked records). No-WAL stores
        report zero checks."""
        result = {"check": "wal", "checked": 0, "violations": [],
                  "abstained": 0}
        wal = getattr(store, "_wal", None)
        if wal is None:
            return result
        live: dict[str, int] = {}
        for name, st in list(store._types.items()):
            with st.lock:
                live[name] = st.wal_seq
        # high-water read AFTER the applied seqs: a write landing between
        # the two reads makes the (stale) seq <= the (fresh) high-water —
        # the reverse order false-alarmed on every concurrent write
        high = wal.seq_highwater()
        for name, seq in live.items():
            result["checked"] += 1
            if seq > high:
                result["violations"].append(
                    f"{name}: applied wal_seq {seq} > seq high-water {high}")
        try:
            for topic in wal.topics():
                result["checked"] += 1
                head = wal.bus.head_offset(topic)
                # the RAW sidecar value: committed_offset() clamps to
                # max(commit, head), which would make this check
                # unfalsifiable
                raw = wal.bus._read_commit(topic)
                if raw is None:
                    result["abstained"] += 1
                elif head > raw:
                    result["violations"].append(
                        f"{topic}: trimmed head {head} > commit {raw}")
        except OSError:
            result["abstained"] += 1
        catalog = getattr(store, "_wal_catalog", None)
        if catalog:
            import json as _json
            import os as _os

            from geomesa_tpu.store import persistence as _persist
            from geomesa_tpu.store import wal as _walmod

            mpath = _os.path.join(catalog, _persist.MANIFEST)
            try:
                stamps = (_json.loads(open(mpath).read())
                          .get("wal", {}).get("topics", {}))
            except (OSError, ValueError):
                stamps = {}
            for topic, stamp in stamps.items():
                name = _walmod.type_for(topic)
                if name is None or name not in live:
                    continue
                result["checked"] += 1
                if int(stamp) > live[name]:
                    # a concurrent checkpoint can stamp between our two
                    # reads — re-read before concluding (and the schema
                    # may have been deleted meanwhile: abstain, the next
                    # checkpoint drops its stamp)
                    st2 = store._types.get(name)
                    if st2 is None:
                        result["abstained"] += 1
                        continue
                    with st2.lock:
                        now = st2.wal_seq
                    if int(stamp) > now:
                        result["violations"].append(
                            f"{topic}: manifest stamp {stamp} > live "
                            f"applied seq {now}")
        return result

    def check_pyramids(self, store) -> dict:
        """Pyramid partials reconcile against base per (bin, cell) on a
        rotating cell sample: the finest level's per-group counts for K
        sampled (bin, cell) buckets must equal a fresh recount from the
        main tier (the same normalization the build used). Abstains when
        the epoch moves mid-check or no pyramid is live."""
        import numpy as np

        result = {"check": "pyramid", "checked": 0, "violations": [],
                  "abstained": 0}
        for name, st in list(store._types.items()):
            epoch = st.data_epoch()
            with st.lock:
                pyrs = dict(st.pyramids)
                main = st.table
            if main is None or not pyrs:
                continue
            for pkey, (pyr, stamp) in pyrs.items():
                if pyr is None:
                    continue
                if stamp != epoch[0]:
                    result["abstained"] += 1
                    continue
                try:
                    from geomesa_tpu.curve.binned_time import BinnedTime
                    from geomesa_tpu.curve.normalize import (
                        lat as norm_lat,
                        lon as norm_lon,
                    )
                    from geomesa_tpu.ops.geoblocks import COORD_BITS
                    from geomesa_tpu.store.backends import REFINE_PRECISION

                    col = main.geom_column()
                    xi = norm_lon(REFINE_PRECISION).normalize(
                        col.x).astype(np.int64)
                    yi = norm_lat(REFINE_PRECISION).normalize(
                        col.y).astype(np.int64)
                    if st.sft.dtg_field:
                        bins, _ = BinnedTime(
                            st.sft.z3_interval
                        ).to_bin_and_offset(main.dtg_millis())
                    else:
                        bins = np.zeros(len(main), dtype=np.int64)
                    fine = pyr.levels[-1]
                    nx = fine.nx
                    c = nx * nx
                    shift = COORD_BITS - fine.k
                    cell = (yi >> shift) * nx + (xi >> shift)
                    ti = np.searchsorted(pyr.bins_present,
                                         np.asarray(bins, np.int64))
                    t_n = len(pyr.bins_present)
                    total = t_n * c
                    g = max(len(pyr.keys), 1)
                    bucket = ti * c + cell
                    if total * g <= self.FULL_COMPARE_CELLS:
                        # small pyramid: one vectorized full recount —
                        # every (bin, cell, group) partial compared
                        expect = np.bincount(
                            bucket * g + pyr.gid.astype(np.int64),
                            minlength=total * g).astype(np.int64)
                        got = fine.cnt.reshape(-1).astype(np.int64)
                        bad = np.nonzero(expect != got)[0]
                        result["checked"] += total
                        for b in bad[:4]:
                            tb = int(b) // (c * g)
                            cb = (int(b) // g) % c
                            result["violations"].append(
                                f"{name}{list(pkey)}: (bin {tb}, cell "
                                f"{cb}) pyramid={int(got[b])} "
                                f"base={int(expect[b])}")
                    else:
                        # big pyramid: rotating (bin, cell) sample — the
                        # sweep covers the grid over successive passes
                        k = min(self.cell_sample, total)
                        with self._lock:
                            base_cur = self._pyr_cursor
                            self._pyr_cursor = (
                                (base_cur + k) % max(total, 1))
                        sample = (base_cur + np.arange(k)) % total
                        for b in sample:
                            tb, cb = int(b) // c, int(b) % c
                            rows = np.nonzero(bucket == b)[0]
                            expect = np.bincount(
                                pyr.gid[rows], minlength=g,
                            ).astype(np.int64)
                            got = fine.cnt[tb, cb, :].astype(np.int64)
                            result["checked"] += 1
                            if not np.array_equal(expect, got):
                                result["violations"].append(
                                    f"{name}{list(pkey)}: (bin {tb}, "
                                    f"cell {cb}) pyramid={got.sum()} "
                                    f"base={expect.sum()}")
                except (TypeError, ValueError):
                    result["abstained"] += 1
                    continue
                if st.data_epoch() != epoch:
                    # a mutation landed mid-recount: the comparison read
                    # torn state — retract anything it concluded
                    result["violations"] = [
                        v for v in result["violations"]
                        if not v.startswith(f"{name}[")]
                    result["abstained"] += 1
        result["abstained"] = int(result["abstained"])
        return result

    def check_ledger(self, store) -> dict:
        """Devmon-ledger vs ``TpuBackend.residency()`` agreement: every
        byte the live backend state holds must be registered (spatial/
        bbox groups), and the ledger must not exceed residency by more
        than the pool's reclaimable donation stash."""
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.store.backends import TpuBackend

        result = {"check": "ledger", "checked": 0, "violations": [],
                  "abstained": 0}
        if not isinstance(store.backend, TpuBackend):
            return result
        res = devmon.ledger().resident()
        pool = getattr(store.backend, "pool", None)
        for name, st in list(store._types.items()):
            epoch = st.data_epoch()
            per_index = store.device_residency(name)["indices"]
            led = res.get(name, {})
            donated = 0
            if pool is not None:
                with pool._lock:
                    donated = sum(
                        e.nbytes for (t, _i, _f), e in
                        pool._donated.items() if t == name)
            for idx, nbytes in per_index.items():
                result["checked"] += 1
                groups = led.get(idx, {})
                reg = (groups.get(devmon.GROUP_SPATIAL, 0)
                       + groups.get(devmon.GROUP_BBOX, 0))
                if reg < nbytes:
                    if st.data_epoch() != epoch:
                        result["abstained"] += 1
                        continue
                    result["violations"].append(
                        f"{name}.{idx}: resident {nbytes} B but ledger "
                        f"registers {reg} B (unaccounted residency)")
                elif reg > nbytes + donated:
                    if st.data_epoch() != epoch:
                        result["abstained"] += 1
                        continue
                    result["violations"].append(
                        f"{name}.{idx}: ledger {reg} B exceeds resident "
                        f"{nbytes} B + donated {donated} B (leak)")
        return result

    def check_query_cache(self, store) -> dict:
        """Query-cache entry epochs still valid: an entry may be stale
        (it will miss and drop) but must never be stamped AHEAD of the
        live epoch (a future stamp would serve a dead table's answer
        once the epoch catches up) and must never outlive its schema
        (the delete/rename purge discipline)."""
        result = {"check": "query_cache", "checked": 0, "violations": [],
                  "abstained": 0}
        entries = store.agg_cache.entries_snapshot()
        for type_name, _key, epoch in entries:
            result["checked"] += 1
            st = store._types.get(type_name)
            if st is None:
                result["violations"].append(
                    f"cache entry for deleted/renamed schema "
                    f"{type_name!r} (epoch {epoch})")
                continue
            live = st.data_epoch()
            if tuple(epoch) > tuple(live):
                result["violations"].append(
                    f"{type_name}: entry epoch {tuple(epoch)} ahead of "
                    f"live {tuple(live)}")
        return result

    def check_shard_coverage(self, view) -> dict:
        """Sharded-view Z-domain coverage: the shard cuts partition the
        62-bit Z2 domain (disjoint and total) and every shard is owned
        by exactly one live member."""
        result = {"check": "shard_coverage", "checked": 1,
                  "violations": [], "abstained": 0}
        router = getattr(view, "router", None)
        if router is None:
            result["checked"] = 0
            return result
        result["violations"] = router.coverage_violations()
        return result

    def check_tiering(self, pool) -> dict:
        """Buffer-pool tier coherence (serving/elastic.py): a demoted
        (type, index) lives in exactly one lower tier, the warm tier
        respects its RAM budget, cold entries have their on-disk file,
        and demoted bytes are not still ledgered as device-resident —
        a two-tier copy or a stale ledger row would make the ops surface
        report HBM the device freed long ago."""
        result = {"check": "tiering", "checked": 1,
                  "violations": [], "abstained": 0}
        tier = getattr(pool, "_tiering", None)
        if tier is None:
            result["checked"] = 0
            return result
        result["violations"] = tier.coherence_violations()
        return result

    def check_matrix_sentinels(self, matrix) -> dict:
        """Subscription-matrix masked slots hold the unsatisfiable
        sentinel payload — a freed slot that could still match would
        deliver ghost hits to a dead subscription's successor."""
        result = {"check": "matrix_sentinels", "checked": 1,
                  "violations": [], "abstained": 0}
        result["violations"] = matrix.validate_sentinels()
        return result

    def check_track_state(self, state) -> dict:
        """Trajectory track-state CSR invariants (trajectory/state.py):
        entity offsets start at 0, never decrease, and sum to the row
        count; every entity's timestamps are nondecreasing in layout
        order — a violated CSR silently mis-aggregates EVERY per-entity
        statistic, which no query-level shadow check can see."""
        result = {"check": "track_state", "checked": 1,
                  "violations": [], "abstained": 0,
                  "type_name": getattr(state, "type_name", "")}
        result["violations"] = state.validate()
        return result

    def check_standing_counts(self, store) -> dict:
        """A standing query's cumulative delivered count cross-checked
        against ``store.query`` at the same epoch: delivered < exact is
        a missed delivery (contract violation); delivered > exact is the
        documented quantization superset (recorded, passing). Abstains
        unless the hub is fully drained and quiet around the check, and
        only audits subscriptions that observed the whole stream
        (registered before any ingest, or first-with-backlog-replay)."""
        result = {"check": "standing_counts", "checked": 0,
                  "violations": [], "abstained": 0, "loose_extra": 0}
        hubs = getattr(store, "_hubs", None)
        if hubs is None:
            return result
        for type_name, hub in hubs.items():
            if hub.lag() != 0:
                result["abstained"] += 1
                continue
            before = hub.rows_ingested()
            for sid, predicate in hub.matrix.standing():
                if predicate is None:
                    continue
                if hub.sub_base(sid) != 0:
                    result["abstained"] += 1
                    continue
                delivered = hub.scanner.total(sid)
                try:
                    exact = store.query(type_name, predicate).count
                except Exception:  # noqa: BLE001 — abstain on any query trouble
                    result["abstained"] += 1
                    continue
                if hub.rows_ingested() != before or hub.lag() != 0:
                    result["abstained"] += 1
                    continue
                result["checked"] += 1
                if delivered < exact:
                    result["violations"].append(
                        f"{type_name} sid={sid}: delivered {delivered} "
                        f"< exact {exact} (missed deliveries)")
                elif delivered > exact:
                    result["loose_extra"] += 1
        return result


# -- repro-bundle replay ------------------------------------------------------

def load_bundle(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("kind") != "geomesa-audit-repro-bundle":
        raise ValueError(f"{path!r} is not an audit repro bundle")
    return doc


def replay_bundle(store, path_or_doc) -> dict:
    """Re-execute a repro bundle against ``store``: run the live path
    and the referee for both the original and the minimized predicate
    (all in shadow — replay must not pollute the feedback planes) and
    report whether the divergence still reproduces."""
    from geomesa_tpu.ops import referee as _referee
    from geomesa_tpu.planning.planner import Query

    doc = (path_or_doc if isinstance(path_or_doc, dict)
           else load_bundle(path_or_doc))
    ev = doc["event"]
    type_name = ev["type"]
    check = doc.get("check", "select")

    def run_one(filt_text: str) -> dict:
        q = Query(filter=None if filt_text == "INCLUDE" else filt_text,
                  hints=dict(ev.get("hints") or {}),
                  auths=(list(ev["auths"])
                         if ev.get("auths") is not None else None))
        with shadow():
            st = store._types[type_name]
            if check == "agg":
                out = store.aggregate_many(
                    type_name, [q], group_by=doc.get("group_by"),
                    value_cols=doc.get("value_cols") or ())
                main, _i, _b, _s, delta = st.snapshot()
                ref = _referee.referee_agg(
                    st.sft, main, delta, q, doc.get("group_by"),
                    doc.get("value_cols") or (),
                    cutoff_ms=doc.get("cutoff_ms"))
                if out[0] is None:
                    # the live engine declined the batched path: the
                    # caller-side host fold IS the referee — no divergence
                    return {"filter": filt_text, "diverged": False,
                            "declined": True}
                lm = _referee.live_agg_map(
                    out[0], list(doc.get("value_cols") or ()))
                ok, detail = _referee.agg_equal(lm, ref)
                return {"filter": filt_text, "diverged": not ok,
                        "detail": detail}
            if check == "count":
                # the divergence came from the BATCHED exact-count lane:
                # replay it through count_many, not the select path
                live_n = int(store.count_many(
                    type_name, [q], loose=False)[0])
            else:
                live = store.query(type_name, q)
                live_n = live.count
            main, _i, _b, _s, delta = st.snapshot()
            ref_fids = _referee.referee_select(
                st.sft, main, delta, q)
            if check == "count":
                ok = live_n == len(ref_fids)
                detail = "" if ok else (
                    f"count live={live_n} referee={len(ref_fids)}")
            else:
                ok, detail = _referee.fid_sets_equal(
                    sorted(str(f) for f in live.table.fids), ref_fids)
            return {"filter": filt_text, "diverged": not ok,
                    "detail": detail,
                    "live_rows": live_n,
                    "referee_rows": len(ref_fids)}

    original = run_one(ev.get("filter") or "INCLUDE")
    minimized = None
    if doc.get("minimized") and doc["minimized"] != ev.get("filter"):
        minimized = run_one(doc["minimized"])
    return {
        "kind": "audit-bundle-replay",
        "check": check,
        "type": type_name,
        "recorded_detail": doc.get("detail", ""),
        "original": original,
        "minimized": minimized,
        "reproduced": bool(
            original["diverged"]
            or (minimized is not None and minimized["diverged"])),
    }


# -- process-wide singletons --------------------------------------------------

_auditor = ContinuousAuditor()


def get() -> ContinuousAuditor:
    return _auditor


def install(auditor: "ContinuousAuditor | None") -> ContinuousAuditor:
    """Swap the process auditor (tests / reconfiguration); returns the
    previous one. ``install(None)`` resets to a fresh env-configured
    auditor. The outgoing auditor's worker stops; installing an auditor
    that was previously swapped OUT (``install(old)``) revives it —
    its worker restarts on the next enqueue and ITS sampling rate is
    re-applied, so a swap-back restores coverage instead of silently
    enqueueing into a dead worker at the swapped-in rate."""
    global _auditor
    prev = _auditor
    if auditor is None:
        set_rate(_env_rate())
        auditor = ContinuousAuditor()
    else:
        set_rate(auditor.rate)
        with auditor._lock:
            if auditor._stop.is_set():  # closed by a prior swap-out
                auditor._stop = threading.Event()
                auditor._thread = None
    _auditor = auditor
    prev.close()
    return prev
