"""Device telemetry: HBM residency ledger, per-query device-time
attribution, and the online cost-profile table.

The rest of the obs stack sees the *host* pipeline end to end; this module
opens the device black box with three substrates (docs/observability.md
§ Device telemetry & cost profiles):

- :class:`ResidencyLedger` — every device allocation the backend makes
  (:meth:`geomesa_tpu.store.backends.TpuBackend.load`, the grouped-agg
  staging cache) registers here as (type, index, column group, bytes),
  and unregisters automatically when the owning state object is dropped
  (eviction, reload, compaction — a ``weakref.finalize`` per entry, so no
  invalidation protocol can be forgotten). Exposes live
  ``geomesa_device_resident_bytes{type,index,group}`` gauges, headroom
  against the backend's ``max_device_bytes`` budget, and the
  host-resident-spill report — the accounting layer a buffer-pool
  eviction policy (ROADMAP item 1) sits on.

- :func:`profiled` / :class:`DevProfile` — the sampled per-query
  device-time attribution mode (``GEOMESA_TPU_DEVPROF`` env or the
  ``devprof`` query hint). While a profile is active on the context,
  :func:`geomesa_tpu.obs.jaxmon.observed` brackets each cached-jit
  dispatch with ``block_until_ready`` timing so the query's wall time
  splits into compile / dispatch / device-compute / h2d / d2h. The
  OFF path costs one module-global flag check per dispatch (the <2%
  bound on the cached-jit select path is asserted in
  ``tests/test_devmon.py`` and gated in ``scripts/lint.sh``).

- :class:`CostTable` — attribution records aggregate into an online
  per-(type, plan-signature) cost profile (p50/p95 device-ms and wall-ms,
  bytes scanned, rows returned), served at ``GET /api/obs/costs`` and
  rendered by ``explain(analyze=True)`` as predicted-vs-actual. Read-only
  for now: it is exactly the observed-cost table the adaptive planner
  (ROADMAP item 3) will consume.

Locking: the ledger and cost table each own one leaf lock (same tier as
the metrics-registry locks — docs/concurrency.md); no blocking calls run
under either. No jax at module level (``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import math
import os
import random
import threading
import weakref
from contextlib import contextmanager
from contextvars import ContextVar

from geomesa_tpu.analysis.contracts import cache_surface, feedback_sink

__all__ = [
    "DEVPROF_ENV", "CostTable", "DevProfile", "ResidencyLedger",
    "cost_sidecar_path", "costs", "current_profile", "device_report",
    "install", "ledger", "load_cost_snapshot", "plan_signature",
    "profiled", "prometheus_text", "purge_persisted_costs",
    "sampled", "save_cost_snapshot",
]

DEVPROF_ENV = "GEOMESA_TPU_DEVPROF"

# canonical column-group names (the residency unit ROADMAP item 1's
# eviction policy will reason about)
GROUP_SPATIAL = "spatial"  # x/y/bins/offs point layout
GROUP_BBOX = "bbox"  # xmin/ymin/xmax/ymax/bins/offs overlap layout
GROUP_AGG = "agg"  # grouped-aggregation staging (gid/rowid/value cols)
GROUP_PYRAMID = "pyramid"  # GeoBlocks pre-aggregation pyramid levels


# -- HBM residency ledger -----------------------------------------------------

@cache_surface(name="spill-ledger", keyed_by="type_name",
               purge=("clear_spills",))
class ResidencyLedger:
    """Process-wide registry of live device allocations.

    Entries are (type, index, group, bytes), keyed by an opaque token;
    when an ``owner`` object is supplied at registration the entry
    auto-unregisters when that object is garbage collected — the drop /
    donate / reload paths need no explicit bookkeeping, they just stop
    referencing the old state. One leaf lock; every method is O(entries)
    or better and never blocks under it."""

    def __init__(self):
        self._lock = threading.Lock()  # leaf: entries + spills + budget
        self._seq = 0
        self._entries: dict[int, tuple] = {}  # token -> (type, index, group, bytes)
        self._finalizers: dict[int, object] = {}
        # host-resident spill report: (type, index) -> estimated bytes the
        # budget refused (the index serves from the host path instead)
        self._spills: dict[tuple, int] = {}
        self._budget: int | None = None
        self.register_count = 0  # lifetime registrations (ops surface)

    # -- write surface (the backend's side) -----------------------------------
    def set_budget(self, budget_bytes: int | None) -> None:
        with self._lock:
            self._budget = budget_bytes

    def begin_load(self, type_name: str) -> None:
        """A fresh load for ``type_name`` is starting: clear its spill
        report (the load re-records any indexes that still don't fit)."""
        self.clear_spills(type_name)

    def register(self, type_name: str, index: str, group: str,
                 nbytes: int, owner=None) -> int:
        """Record one live device allocation; returns the entry token.
        With ``owner``, the entry unregisters itself when ``owner`` is
        garbage collected (the state-object lifetime IS the allocation
        lifetime for every backend path)."""
        with self._lock:
            self._seq += 1
            token = self._seq
            self._entries[token] = (type_name, index, group, int(nbytes))
            self.register_count += 1
        if owner is not None:
            fin = weakref.finalize(owner, self.unregister, token)
            fin.atexit = False  # telemetry: never delay interpreter exit
            with self._lock:
                self._finalizers[token] = fin
        return token

    def unregister(self, token: int) -> None:
        with self._lock:
            self._entries.pop(token, None)
            self._finalizers.pop(token, None)

    def unregister_matching(self, type_name: str, index: str) -> int:
        """Drop every live entry of one ``(type, index)`` — the tiering
        policy's demotion path (serving/elastic.py): the owner object
        stays ALIVE holding host/disk copies, so its GC finalizer cannot
        fire, yet the bytes have left the device and must leave the
        ledger with them (the ledger-vs-residency agreement pinned in
        tests). The orphaned finalizers later no-op against the already-
        removed tokens. Returns the bytes unregistered."""
        with self._lock:
            tokens = [
                t for t, e in self._entries.items()
                if e[0] == type_name and e[1] == index
            ]
            freed = 0
            for t in tokens:
                freed += self._entries.pop(t)[3]
                self._finalizers.pop(t, None)
            return freed

    def record_spill(self, type_name: str, index: str, est_bytes: int) -> None:
        with self._lock:
            self._spills[(type_name, index)] = int(est_bytes)

    def clear_spills(self, type_name: str) -> None:
        with self._lock:
            for k in [k for k in self._spills if k[0] == type_name]:
                del self._spills[k]

    # -- read surface ---------------------------------------------------------
    def total_bytes(self) -> int:
        with self._lock:
            return sum(e[3] for e in self._entries.values())

    def type_bytes(self, type_name: str) -> int:
        with self._lock:
            return sum(
                e[3] for e in self._entries.values() if e[0] == type_name
            )

    def index_bytes(self, type_name: str, index: str) -> int:
        """Live device bytes held by one (type, index) across groups —
        the bytes-scanned denominator the cost table records."""
        with self._lock:
            return sum(
                e[3] for e in self._entries.values()
                if e[0] == type_name and e[1] == index
            )

    def resident(self) -> dict:
        """``{type: {index: {group: bytes}}}`` for every live entry
        (entries sharing a key sum — reload overlap windows show both)."""
        out: dict = {}
        with self._lock:
            entries = list(self._entries.values())
        for t, i, g, b in entries:
            grp = out.setdefault(t, {}).setdefault(i, {})
            grp[g] = grp.get(g, 0) + b
        return out

    def snapshot(self) -> dict:
        """The ``device`` section of ``/api/metrics``: per-(type, index,
        group) resident bytes, budget headroom, and the spill report.

        ``budget_bytes`` applies PER TYPE (the ``TpuBackend`` contract —
        a store holding T types can reach T × budget), so
        ``headroom_bytes`` reports the MOST CONSTRAINED type: budget
        minus the largest per-type total. A single-type process reads it
        as plain budget-minus-resident."""
        with self._lock:
            entries = list(self._entries.values())
            spills = dict(self._spills)
            budget = self._budget
            registered = self.register_count
        resident: dict = {}
        total = 0
        per_type: dict = {}
        for t, i, g, b in entries:
            grp = resident.setdefault(t, {}).setdefault(i, {})
            grp[g] = grp.get(g, 0) + b
            per_type[t] = per_type.get(t, 0) + b
            total += b
        return {
            "resident": resident,
            "total_bytes": total,
            "budget_bytes": budget,
            "headroom_bytes": (
                budget - max(per_type.values(), default=0)
                if budget is not None else None
            ),
            "spilled": {f"{t}.{i}": b for (t, i), b in spills.items()},
            "spilled_bytes": sum(spills.values()),
            "register_count": registered,
        }

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        """Labeled residency gauges (the exposition
        :func:`geomesa_tpu.obs.export.prometheus_text` cannot emit —
        appended to the scrape the way the SLO engine's lines are)."""
        snap = self.snapshot()
        lines = [f"# TYPE {prefix}_device_resident_bytes gauge"]
        for t, per_index in sorted(snap["resident"].items()):
            for i, per_group in sorted(per_index.items()):
                for g, b in sorted(per_group.items()):
                    lines.append(
                        f'{prefix}_device_resident_bytes'
                        f'{{type="{t}",index="{i}",group="{g}"}} {b}'
                    )
        lines.append(f"# TYPE {prefix}_device_resident_bytes_total gauge")
        lines.append(
            f"{prefix}_device_resident_bytes_total {snap['total_bytes']}")
        if snap["budget_bytes"] is not None:
            lines.append(f"# TYPE {prefix}_device_budget_bytes gauge")
            lines.append(
                f"{prefix}_device_budget_bytes {snap['budget_bytes']}")
            lines.append(f"# TYPE {prefix}_device_headroom_bytes gauge")
            lines.append(
                f"{prefix}_device_headroom_bytes {snap['headroom_bytes']}")
        if snap["spilled"]:
            lines.append(f"# TYPE {prefix}_device_spilled_bytes gauge")
            for key, b in sorted(snap["spilled"].items()):
                t, _, i = key.rpartition(".")
                lines.append(
                    f'{prefix}_device_spilled_bytes'
                    f'{{type="{t}",index="{i}"}} {b}'
                )
        return lines


# -- per-query device-time attribution ---------------------------------------

class DevProfile:
    """Accumulator for one profiled query's device-time attribution.

    Stage totals (ms): ``compile`` (cold jit trace+lower+compile),
    ``dispatch`` (warm host-side dispatch until the async call returns),
    ``device_compute`` (``block_until_ready`` wait), ``h2d`` (timed
    host→device staging of numpy arguments), ``d2h`` (timed
    materialization of results back to host). Byte counters ride along.
    Locked: the watchdog may run the scan on a worker thread while the
    caller's thread owns the context (contexts are copied into workers)."""

    __slots__ = ("_lock", "compile_ms", "dispatch_ms", "device_ms",
                 "h2d_ms", "d2h_ms", "h2d_bytes", "d2h_bytes",
                 "dispatches", "compiles", "steps")

    def __init__(self):
        self._lock = threading.Lock()
        self.compile_ms = 0.0
        self.dispatch_ms = 0.0
        self.device_ms = 0.0
        self.h2d_ms = 0.0
        self.d2h_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.dispatches = 0
        self.compiles = 0
        self.steps: dict[str, dict] = {}

    def note_h2d(self, nbytes: int) -> None:
        """Attribute pre-staged payload bytes (``jaxmon.count_h2d`` with a
        query-side label) to THIS query, without counting a dispatch.
        Pool-labeled staging (a buffer-pool warm-up the query merely
        triggered) never lands here — per-query h2d splits stay truthful."""
        with self._lock:
            self.h2d_bytes += int(nbytes)

    def add(self, step: str, *, compile_ms=0.0, dispatch_ms=0.0,
            device_ms=0.0, h2d_ms=0.0, d2h_ms=0.0,
            h2d_bytes=0, d2h_bytes=0) -> None:
        with self._lock:
            self.compile_ms += compile_ms
            self.dispatch_ms += dispatch_ms
            self.device_ms += device_ms
            self.h2d_ms += h2d_ms
            self.d2h_ms += d2h_ms
            self.h2d_bytes += h2d_bytes
            self.d2h_bytes += d2h_bytes
            self.dispatches += 1
            if compile_ms:
                self.compiles += 1
            s = self.steps.setdefault(
                step, {"calls": 0, "ms": 0.0, "device_ms": 0.0})
            s["calls"] += 1
            s["ms"] += compile_ms + dispatch_ms + device_ms
            s["device_ms"] += device_ms

    @property
    def total_ms(self) -> float:
        return (self.compile_ms + self.dispatch_ms + self.device_ms
                + self.h2d_ms + self.d2h_ms)

    def breakdown(self) -> dict:
        """The flight-record / explain payload: stage → ms splits plus
        transfer bytes and dispatch counts."""
        with self._lock:
            return {
                "compile": round(self.compile_ms, 3),
                "dispatch": round(self.dispatch_ms, 3),
                "device_compute": round(self.device_ms, 3),
                "h2d": round(self.h2d_ms, 3),
                "d2h": round(self.d2h_ms, 3),
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "dispatches": self.dispatches,
                "compiles": self.compiles,
                "steps": {k: dict(v) for k, v in self.steps.items()},
            }


_prof_var: ContextVar["DevProfile | None"] = ContextVar(
    "geomesa_devprof", default=None)
_active_lock = threading.Lock()
_active_count = 0
# THE one check jaxmon.observed pays per dispatch when profiling is off:
# a module-global bool, flipped only while >=1 profiled() context is live
PROFILING = False

# deterministic-enough per-process sampler stream (independent of the
# global random state so tests that seed random stay unperturbed)
_sampler = random.Random()
_sampler_lock = threading.Lock()


def env_rate() -> float:
    """The ``GEOMESA_TPU_DEVPROF`` sampling rate: unset/0 → off, ``1`` /
    ``true`` → every query, a float in (0, 1] → that fraction. Read per
    call so operators (and tests) can flip it live."""
    raw = os.environ.get(DEVPROF_ENV, "").strip().lower()
    if not raw or raw in ("0", "false", "off", "no"):
        return 0.0
    if raw in ("1", "true", "on", "yes"):
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def sampled(hint=None) -> bool:
    """Should THIS query be device-profiled? An explicit per-query hint
    (``hints={"devprof": True/False}``) always wins; otherwise sample at
    the env rate."""
    if hint is not None:
        return bool(hint)
    rate = env_rate()
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    with _sampler_lock:
        return _sampler.random() < rate


def current_profile() -> "DevProfile | None":
    """The live profile on this context (None when this query is not
    being profiled). Callers gate on :data:`PROFILING` first so the off
    path never pays the ContextVar read."""
    return _prof_var.get()


@contextmanager
def profiled():
    """Activate device-time attribution for this call tree. Nested
    activations share the OUTER profile (``explain(analyze=True)``
    wraps ``query()``, which may itself sample — the records must land
    in one accumulator, not split across two)."""
    global PROFILING
    existing = _prof_var.get()
    if existing is not None:
        yield existing
        return
    prof = DevProfile()
    with _active_lock:
        _active_count_inc()
    tok = _prof_var.set(prof)
    try:
        yield prof
    finally:
        _prof_var.reset(tok)
        with _active_lock:
            _active_count_dec()


def _active_count_inc():
    global _active_count, PROFILING
    _active_count += 1
    PROFILING = True


def _active_count_dec():
    global _active_count, PROFILING
    _active_count -= 1
    PROFILING = _active_count > 0


# -- plan signatures ----------------------------------------------------------

def plan_signature(info, q=None) -> str:
    """The cost-table key for one executed plan: index choice, union arm
    count, aggregation kind, and a log2 bucket of the interval count —
    the plan *shape*, not the literal predicate, so repeated queries of
    the same shape share one cost profile (what the adaptive planner
    needs: costs per strategy, not per filter string)."""
    agg = "rows"
    if q is not None:
        hints = getattr(q, "hints", None) or {}
        for kind in ("density", "stats", "bin"):
            if hints.get(kind):
                agg = kind
                break
    if info is None:
        return f"scan:{agg}"
    parts = [getattr(info, "index_name", None) or "none"]
    n_iv = getattr(info, "n_intervals", 0)
    if n_iv:
        # next-power-of-two bucket: plan WIDTH matters, exact count is noise
        parts.append(f"iv{1 << max(int(n_iv) - 1, 0).bit_length()}")
    parts.append(agg)
    return ":".join(parts)


# -- online cost profiles -----------------------------------------------------

class _Quantiles:
    """Bounded reservoir (algorithm R) + count/sum — the same shape as
    :class:`geomesa_tpu.utils.metrics.Histogram` without the import (this
    module stays dependency-free for ``GEOMESA_TPU_NO_JAX`` processes).
    NOT thread-safe on its own: the owning :class:`CostTable` lock guards
    every update/read."""

    __slots__ = ("count", "total", "_res", "_rng", "_qcache")
    SIZE = 256
    # above this many observations, quantiles serve from a cache refreshed
    # every CACHE_DELTA updates — predict() rides the per-query audit hot
    # path (the adaptive planner consults it every dispatch) and a fresh
    # reservoir sort per call would erode the <2% overhead bound. Below
    # the threshold quantiles stay exact (small-sample tests pin values).
    CACHE_MIN = 64
    CACHE_DELTA = 16

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self._res: list[float] = []
        self._rng = random.Random(0x5DEECE66D)
        self._qcache: dict[float, tuple[int, float]] = {}

    def to_state(self) -> dict:
        """JSON-able state (cost-profile persistence — the reservoir IS
        the learned distribution; the RNG restarts, which only changes
        which FUTURE samples replace which slots)."""
        return {"count": self.count, "total": self.total,
                "res": [round(v, 4) for v in self._res]}

    def load_state(self, state: dict) -> None:
        self.count = int(state.get("count", 0))
        self.total = float(state.get("total", 0.0))
        self._res = [float(v) for v in state.get("res", [])][:self.SIZE]
        self._qcache = {}

    def update(self, v: float) -> None:
        self.count += 1
        self.total += v
        if len(self._res) < self.SIZE:
            self._res.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.SIZE:
                self._res[j] = v

    def quantile(self, q: float) -> float:
        if not self._res:
            return 0.0
        hit = self._qcache.get(q)
        if (hit is not None and self.count > self.CACHE_MIN
                and self.count - hit[0] < self.CACHE_DELTA):
            return hit[1]
        s = sorted(self._res)
        pos = q * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        v = s[lo] * (1.0 - frac) + s[hi] * frac
        self._qcache[q] = (self.count, v)
        return v


class _CostEntry:
    __slots__ = ("wall_ms", "device_ms", "rows", "bytes_scanned", "count",
                 "profiled_count")

    def __init__(self):
        self.wall_ms = _Quantiles()
        self.device_ms = _Quantiles()
        self.rows = _Quantiles()
        self.bytes_scanned = _Quantiles()
        self.count = 0
        self.profiled_count = 0


@cache_surface(name="device-cost-table", keyed_by="type_name",
               purge=("forget",))
class CostTable:
    """Online per-(type, plan-signature) observed-cost aggregation.

    Every completed query observes wall-ms / rows / bytes-scanned; queries
    that ran under :func:`profiled` additionally observe device-ms. Read
    surfaces: :meth:`snapshot` (``GET /api/obs/costs``) and
    :meth:`predict` (``explain`` predicted-vs-actual). Bounded: least-
    recently-observed signatures evict past ``max_entries``."""

    def __init__(self, max_entries: int = 512):
        from collections import OrderedDict

        self._lock = threading.Lock()  # leaf: the entry table
        self._entries: "OrderedDict[tuple, _CostEntry]" = OrderedDict()
        self._ticks: dict[tuple, int] = {}
        self.max_entries = max_entries

    def tick(self, type_name: str, name: str) -> int:
        """Monotonic per-(type, name) consult counter. Routing policies
        (``planner.choose_agg_path``) schedule periodic probes of the
        losing route off this — NOT off observation counts, which the
        winning route freezes by starving the loser of observations."""
        key = (type_name, name)
        with self._lock:
            n = self._ticks.get(key, 0) + 1
            self._ticks[key] = n
        return n

    def forget(self, type_name: str) -> None:
        """Drop every signature row and consult tick of one type. A
        deleted or renamed schema must not hand its observed cost profile
        (or its probe phase) to an unrelated future type of the same
        name."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == type_name]:
                del self._entries[k]
            for k in [k for k in self._ticks if k[0] == type_name]:
                del self._ticks[k]

    @feedback_sink
    def observe(self, type_name: str, signature: str, *,
                wall_ms: float, device_ms: float | None = None,
                rows: int = 0, bytes_scanned: int = 0) -> None:
        if not _finite(wall_ms):
            return  # a clock anomaly must never poison a reservoir
        key = (type_name, signature)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = _CostEntry()
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
            else:
                self._entries.move_to_end(key)
            e.count += 1
            e.wall_ms.update(float(wall_ms))
            e.rows.update(float(rows))
            if bytes_scanned:
                e.bytes_scanned.update(float(bytes_scanned))
            if device_ms is not None:
                e.profiled_count += 1
                e.device_ms.update(float(device_ms))

    def predict(self, type_name: str, signature: str) -> dict | None:
        """The table's current p50 cost for one plan shape (None when the
        shape has never been observed) — what ``explain`` shows as
        *predicted* and the adaptive planner will rank strategies by."""
        with self._lock:
            e = self._entries.get((type_name, signature))
            if e is None:
                return None
            return {
                "wall_ms_p50": round(e.wall_ms.quantile(0.5), 3),
                "wall_ms_p95": round(e.wall_ms.quantile(0.95), 3),
                "device_ms_p50": (
                    round(e.device_ms.quantile(0.5), 3)
                    if e.profiled_count else None
                ),
                "observations": e.count,
            }

    def predict_prefix(self, type_name: str, prefix: str) -> dict | None:
        """Aggregated profile over every signature of one type starting
        with ``prefix`` — how the adaptive planner reads STRATEGY-level
        costs (audit signatures are ``index:ivN:agg``; the strategy
        decision keys by ``index:`` alone). Observation-weighted means of
        the per-signature p50/p95 (a strategy's profile is dominated by
        the shapes it actually serves); None when nothing matches."""
        with self._lock:
            matched = [
                e for (t, sig), e in self._entries.items()
                if t == type_name and sig.startswith(prefix)
            ]
            if not matched:
                return None
            n = sum(e.count for e in matched)
            p50 = sum(e.wall_ms.quantile(0.5) * e.count for e in matched) / n
            p95 = sum(e.wall_ms.quantile(0.95) * e.count for e in matched) / n
            return {
                "wall_ms_p50": round(p50, 3),
                "wall_ms_p95": round(p95, 3),
                "device_ms_p50": None,
                "observations": n,
                "signatures": len(matched),
            }

    # -- persistence (docs/observability.md § Cost-model persistence) ---------
    def to_state(self) -> dict:
        """The table's full learned state as JSON-able data: per-(type,
        signature) reservoirs + counts, plus the consult ticks (probe
        cadence must survive a restart too, or every reopened store
        re-probes from scratch)."""
        with self._lock:
            entries = []
            for (t, sig), e in self._entries.items():
                entries.append({
                    "type": t, "signature": sig, "count": e.count,
                    "profiled_count": e.profiled_count,
                    "wall_ms": e.wall_ms.to_state(),
                    "device_ms": e.device_ms.to_state(),
                    "rows": e.rows.to_state(),
                    "bytes_scanned": e.bytes_scanned.to_state(),
                })
            ticks = [[t, n, v] for (t, n), v in self._ticks.items()]
        return {"entries": entries, "ticks": ticks}

    def load_state(self, state: dict) -> None:
        """Restore a :meth:`to_state` snapshot. Merge by richness: a
        snapshot row only lands when it has MORE observations than the
        live same-key entry — loading at store open must not wipe (or
        regress) profiles another open store already learned past the
        snapshot; unrelated live rows are never touched."""
        for row in state.get("entries", []):
            key = (row["type"], row["signature"])
            e = _CostEntry()
            e.count = int(row.get("count", 0))
            e.profiled_count = int(row.get("profiled_count", 0))
            e.wall_ms.load_state(row.get("wall_ms", {}))
            e.device_ms.load_state(row.get("device_ms", {}))
            e.rows.load_state(row.get("rows", {}))
            e.bytes_scanned.load_state(row.get("bytes_scanned", {}))
            with self._lock:
                live = self._entries.get(key)
                if live is not None and live.count >= e.count:
                    continue
                self._entries[key] = e
                self._entries.move_to_end(key)
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
        for t, n, v in state.get("ticks", []):
            with self._lock:
                key = (t, n)
                self._ticks[key] = max(self._ticks.get(key, 0), int(v))

    def snapshot(self, limit: int = 256) -> dict:
        with self._lock:
            items = list(self._entries.items())[-limit:]
            rows = []
            for (t, sig), e in items:
                rows.append({
                    "type": t,
                    "signature": sig,
                    "count": e.count,
                    "profiled": e.profiled_count,
                    "wall_ms_p50": round(e.wall_ms.quantile(0.5), 3),
                    "wall_ms_p95": round(e.wall_ms.quantile(0.95), 3),
                    "device_ms_p50": round(e.device_ms.quantile(0.5), 3),
                    "device_ms_p95": round(e.device_ms.quantile(0.95), 3),
                    "rows_p50": round(e.rows.quantile(0.5), 1),
                    "bytes_scanned_p50": round(
                        e.bytes_scanned.quantile(0.5), 0),
                })
        rows.sort(key=lambda r: (r["type"], r["signature"]))
        return {"entries": rows, "entry_count": len(rows)}


# -- process-wide singletons --------------------------------------------------

_ledger = ResidencyLedger()
_costs = CostTable()


def ledger() -> ResidencyLedger:
    return _ledger


def costs() -> CostTable:
    return _costs


def install(new_ledger: ResidencyLedger | None = None,
            new_costs: CostTable | None = None) -> tuple:
    """Swap the process singletons (test isolation); returns the previous
    (ledger, costs) pair. Entries registered against the OLD ledger keep
    unregistering against it — their finalizers captured the instance."""
    global _ledger, _costs
    prev = (_ledger, _costs)
    if new_ledger is not None:
        _ledger = new_ledger
    if new_costs is not None:
        _costs = new_costs
    return prev


def device_report() -> dict:
    """The ``device`` section of ``/api/metrics``: the residency snapshot
    plus process-wide transfer totals from the jax telemetry registry."""
    out = _ledger.snapshot()
    transfers = {"h2d_bytes": 0, "d2h_bytes": 0}
    from geomesa_tpu.obs import jaxmon

    if jaxmon.GLOBAL is not None:
        snap = jaxmon.GLOBAL.snapshot()
        for k, short in (("jax.transfer.h2d_bytes", "h2d_bytes"),
                         ("jax.transfer.d2h_bytes", "d2h_bytes"),
                         ("jax.transfer.h2d_bytes.pool", "h2d_bytes_pool")):
            if k in snap:
                transfers[short] = snap[k].get("count", 0)
    out["transfers"] = transfers
    out["devprof_rate"] = env_rate()
    return out


def prometheus_text(prefix: str = "geomesa") -> str:
    lines = _ledger.prometheus_lines(prefix)
    return "\n".join(lines) + "\n" if lines else ""


# -- cost-profile persistence (the GEOMESA_TPU_WORKLOAD_DIR sidecar) ----------
# Learned p50 rankings and calibration survive restarts: the cost table
# (+ the cost model's calibration entries) snapshot to costs.json next to
# the workload capture, loaded at store open (store.persistence.load) and
# saved at catalog save. Schema delete/rename purges the persisted rows
# along with the live ones (DataStore._purge_type_name).

COSTS_SIDECAR = "costs.json"


def cost_sidecar_path(path: str | None = None) -> "str | None":
    """The sidecar file path: explicit, or derived from
    ``GEOMESA_TPU_WORKLOAD_DIR`` (None when neither is set)."""
    if path is not None:
        return path
    d = os.environ.get("GEOMESA_TPU_WORKLOAD_DIR") or None
    return os.path.join(d, COSTS_SIDECAR) if d else None


@cache_surface(name="persisted-cost-sidecar", keyed_by="type_name",
               purge=("purge_persisted_costs",))
def save_cost_snapshot(path: str | None = None) -> "str | None":
    """Persist the live cost table + calibration state; returns the path
    written (None when no sidecar location is configured). Atomic
    (tmp + replace): a crash mid-save must not truncate the previous
    snapshot."""
    import json

    p = cost_sidecar_path(path)
    if p is None:
        return None
    from geomesa_tpu.planning import costmodel

    doc = {
        "kind": "geomesa-cost-snapshot",
        "costs": _costs.to_state(),
        "calibration": costmodel.model().calibration_state(),
    }
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    tmp = p + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    os.replace(tmp, p)
    return p


def load_cost_snapshot(path: str | None = None) -> bool:
    """Load a persisted snapshot into the live table + cost model (no-op
    when the sidecar is absent/unreadable — a missing or corrupt snapshot
    must never fail a store open). Returns True when state loaded."""
    import json

    p = cost_sidecar_path(path)
    if p is None or not os.path.exists(p):
        return False
    try:
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return False
    if doc.get("kind") != "geomesa-cost-snapshot":
        return False
    _costs.load_state(doc.get("costs", {}))
    from geomesa_tpu.planning import costmodel

    costmodel.model().load_calibration_state(doc.get("calibration", {}))
    return True


def purge_persisted_costs(type_name: str, path: str | None = None) -> None:
    """Drop one type's rows from the persisted sidecar (schema delete/
    rename: the successor type must not inherit the dead type's learned
    profile across a restart). Best-effort — a read-only sidecar never
    fails the schema operation."""
    import json

    p = cost_sidecar_path(path)
    if p is None or not os.path.exists(p):
        return
    try:
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        costs_state = doc.get("costs", {})
        costs_state["entries"] = [
            e for e in costs_state.get("entries", [])
            if e.get("type") != type_name
        ]
        costs_state["ticks"] = [
            t for t in costs_state.get("ticks", []) if t[0] != type_name
        ]
        cal = doc.get("calibration", {})
        cal["entries"] = [
            e for e in cal.get("entries", [])
            if e.get("type") != type_name
        ]
        tmp = p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, p)
    except (OSError, ValueError):
        return


# math import kept honest: _Quantiles interpolation uses pure arithmetic,
# but a NaN wall-ms (a clock anomaly) must never poison a reservoir
def _finite(v: float) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)
