"""JAX compile/dispatch telemetry (the live counterpart to tpulint J003).

Two hooks:

- :func:`install` registers ``jax.monitoring`` duration listeners so every
  jit compile in the process lands in the telemetry registry as
  ``jax.compile.*`` histograms (trace time, MLIR lowering, backend
  compile). Guarded: a ``GEOMESA_TPU_NO_JAX=1`` process never imports jax
  from here, and a missing/old jax degrades to a no-op.

- :func:`observed` wraps a cached jit step (the ``cached_*_step``
  factories in :mod:`geomesa_tpu.parallel.query`) with a per-call sampler
  that keys calls by ABSTRACT SIGNATURE — the (shape, dtype) tuple jax
  itself caches on. A new signature on an already-warm step is exactly the
  recompile hazard tpulint's J003 flags statically; here it increments
  ``jax.jit.recompiles`` live, with per-step compile/dispatch timing
  histograms and host↔device transfer-byte counters. The sampler itself
  never calls into jax: it reads ``shape``/``dtype``/``nbytes`` attributes
  off whatever arguments arrive and nothing else.

Telemetry is always-on and cheap (~1-2 µs per dispatch, against device
calls that cost milliseconds); SPANS for jit calls are only emitted while
tracing is active (:mod:`geomesa_tpu.obs.trace`).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque

from geomesa_tpu.obs import devmon as _devmon
from geomesa_tpu.obs import ledger as _ledger

__all__ = ["GLOBAL", "registry", "install", "observed", "jit_report",
           "count_h2d", "recompile_census"]

GLOBAL = None  # lazily-created MetricsRegistry (process-wide jax telemetry)
_reg_lock = threading.Lock()
_installed = False


def registry():
    """The process-wide telemetry registry (created on first use)."""
    global GLOBAL
    if GLOBAL is None:
        with _reg_lock:
            if GLOBAL is None:
                from geomesa_tpu.utils.metrics import MetricsRegistry

                GLOBAL = MetricsRegistry()
    return GLOBAL


def _on_duration(name: str, secs: float, **_kw) -> None:
    # e.g. /jax/core/compile/backend_compile_duration → jax.compile.backend_compile
    if "/compile/" not in name:
        return
    tail = name.rsplit("/", 1)[1]
    if tail.endswith("_duration"):
        tail = tail[: -len("_duration")]
    reg = registry()
    reg.histogram(f"jax.compile.{tail}_ms").update(secs * 1000.0)
    reg.counter("jax.compile.events").inc()


def install() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns True when
    listening; False when jax is gated off or unavailable."""
    global _installed
    if _installed:
        return True
    if os.environ.get("GEOMESA_TPU_NO_JAX"):
        return False
    try:
        import jax.monitoring as jm
    except Exception:  # pragma: no cover — no jax in the process
        return False
    with _reg_lock:
        if _installed:
            return True
        # one-time listener registration, not a dispatch: the lock exists
        # precisely to make this registration idempotent under races
        # tpurace: disable-next-line=R003
        jm.register_event_duration_secs_listener(_on_duration)
        _installed = True
    return True


def _abstract_sig(args: tuple) -> tuple:
    """The jit cache key proxy: (shape, dtype) per array argument, type name
    for everything else (python scalars don't retrigger compiles on value)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            out.append((tuple(shape), str(getattr(a, "dtype", ""))))
        else:
            out.append(type(a).__name__)
    return tuple(out)


def _nbytes(obj) -> int:
    """Total nbytes across an array / tuple-of-arrays result (one level of
    tuple/list nesting — the shapes our steps actually return)."""
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, (tuple, list)):
        return sum(_nbytes(x) for x in obj)
    return 0


def _np_bytes(arrays) -> int:
    """Total nbytes across the NUMPY members of ``arrays`` — THE
    host-side-array detection rule for h2d accounting (one definition;
    device-resident jax arrays never count)."""
    return sum(
        int(a.nbytes)
        for a in arrays
        if type(a).__module__.startswith("numpy") and hasattr(a, "nbytes")
    )


# per-thread record of arrays a call site already accounted via count_h2d:
# the next observed() dispatch on the thread must NOT count them again when
# the same numpy array is passed straight into the step. Weak references —
# never ids — so a recycled id after GC can't alias a fresh array, and the
# pending set never pins a multi-GB staging buffer alive.
_h2d_pending = threading.local()
_H2D_PENDING_CAP = 256

# count_h2d labels whose bytes belong to a SHARED subsystem, not to the
# query that happened to be live when they staged: the buffer pool's
# warm-up staging (ISSUE 7) and the stream scanner's chunk pipeline —
# excluded from the devprof h2d split so a concurrent profiled query's
# attribution stays truthful (pinned in tests/test_stream_matrix.py).
_DEVPROF_EXTERNAL = frozenset({"pool", "stream"})


def _note_pending_h2d(arrays) -> None:
    refs = getattr(_h2d_pending, "refs", None)
    if refs is None:
        refs = _h2d_pending.refs = []
    for a in arrays:
        if type(a).__module__.startswith("numpy") and hasattr(a, "nbytes"):
            try:
                refs.append(weakref.ref(a))
            except TypeError:  # un-weakref-able numpy subclass: skip dedupe
                pass
    if len(refs) > _H2D_PENDING_CAP:
        del refs[:-_H2D_PENDING_CAP]


def _consume_pending_h2d() -> set:
    """The identity set of pre-counted arrays, cleared per dispatch (the
    dedupe window IS one dispatch — staged payloads feed the very next
    step)."""
    refs = getattr(_h2d_pending, "refs", None)
    if not refs:
        return set()
    out = {id(a) for a in (r() for r in refs) if a is not None}
    refs.clear()
    return out


def count_h2d(*arrays, label: str | None = None) -> int:
    """Account host→device staging for numpy arrays about to be
    ``jnp.asarray``'d / ``device_put`` / passed to a dispatch (transfers
    the step wrapper cannot see when call sites pre-convert). Non-numpy
    args are skipped — device-resident columns must not be recounted per
    dispatch. Arrays counted here are remembered (weakly, per thread) so
    a call site that passes the SAME numpy array straight into the next
    ``observed()`` dispatch is not double-counted. Returns bytes counted.

    ``label``: attribution bucket, additionally counted under
    ``jax.transfer.h2d_bytes.<label>``. Bytes staged by a SHARED subsystem
    — a buffer-pool warm-up/miss (``label="pool"``) or the stream
    scanner's chunk pipeline (``label="stream"``) — belong to that
    subsystem, not to the query that happened to be live: they are
    excluded from the live devprof profile, so per-query h2d splits stay
    truthful. Unlabeled (query-side) staging IS attributed to the
    profiled query."""
    total = _np_bytes(arrays)
    if total:
        reg = registry()
        reg.counter("jax.transfer.h2d_bytes").inc(total)
        if label:
            reg.counter(f"jax.transfer.h2d_bytes.{label}").inc(total)
        _note_pending_h2d(arrays)
        if label not in _DEVPROF_EXTERNAL and _devmon.PROFILING:
            prof = _devmon.current_profile()
            if prof is not None:
                prof.note_h2d(total)
    return total


# -- recompile census → flight recorder (A_RECOMPILE) -------------------------
# The live J003 dashboard already counts recompiles; the census turns a
# BURST of them into one operator signal: >= GEOMESA_TPU_RECOMPILE_STORM
# recompiles inside a GEOMESA_TPU_RECOMPILE_WINDOW_S window raises ONE
# rate-limited A_RECOMPILE flight anomaly (the recorder's dump throttle
# bounds file output). Recompiles are rare by design (the zero-recompile
# census pins in tests/test_costmodel.py), so this path is cold.
_RECOMPILE_WINDOW_S = float(
    os.environ.get("GEOMESA_TPU_RECOMPILE_WINDOW_S", "60"))
_RECOMPILE_STORM = int(os.environ.get("GEOMESA_TPU_RECOMPILE_STORM", "3"))
_census_lock = threading.Lock()  # leaf: census window + storm clock
_census_times: deque = deque(maxlen=256)  # (ts, step) inside the window
_census_last_storm = -float("inf")
_census_storms = 0


def _note_recompile(step: str) -> None:
    now = time.time()
    burst = 0
    with _census_lock:
        global _census_last_storm, _census_storms
        _census_times.append((now, step))
        horizon = now - _RECOMPILE_WINDOW_S
        while _census_times and _census_times[0][0] < horizon:
            _census_times.popleft()
        n = len(_census_times)
        if (n >= _RECOMPILE_STORM
                and now - _census_last_storm >= _RECOMPILE_WINDOW_S):
            _census_last_storm = now  # one anomaly per window
            _census_storms += 1
            burst = n
    if burst:
        from geomesa_tpu.obs import flight as _flight

        _flight.record(
            "jit.recompile", "", source="jaxmon",
            plan=(f"{burst} recompiles in {_RECOMPILE_WINDOW_S:.0f}s "
                  f"window (latest step: {step})"),
            anomalies=(_flight.A_RECOMPILE,),
        )


def recompile_census() -> dict:
    """The census state (``/api/metrics`` + tests): recompiles inside the
    current window, the storm threshold, and storms raised so far."""
    with _census_lock:
        return {
            "window_s": _RECOMPILE_WINDOW_S,
            "threshold": _RECOMPILE_STORM,
            "in_window": len(_census_times),
            "storms": _census_storms,
        }


def _census_reset() -> None:
    """Test hook: clear the census window and storm clock."""
    with _census_lock:
        global _census_last_storm, _census_storms
        _census_times.clear()
        _census_last_storm = -float("inf")
        _census_storms = 0


def _block_ready(obj) -> None:
    """Wait for every array in ``obj`` (one level of tuple/list nesting —
    the shapes our steps return) to finish on device."""
    if hasattr(obj, "block_until_ready"):
        obj.block_until_ready()
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _block_ready(x)


def _materialize(obj) -> None:
    """Force the device→host copy the caller is about to pay (the d2h
    half of the attribution bracket)."""
    import numpy as np

    if hasattr(obj, "block_until_ready"):
        np.asarray(obj)
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _materialize(x)


def _profiled_call(fn, args, kwargs, sp):
    """The sampled devprof bracket around one dispatch: timed host→device
    staging of numpy arguments (converted here so the step receives
    device arrays — identical semantics, but the transfer is visible),
    the dispatch call itself, a ``block_until_ready`` device wait, and a
    timed device→host materialization of the results. Returns
    ``((h2d_ms, call_ms, device_ms, d2h_ms), out)``. Only ever runs
    inside a live :func:`geomesa_tpu.obs.devmon.profiled` context."""
    import jax

    pc = time.perf_counter
    cm = sp if sp is not None else _NullCtx()
    with cm:
        t0 = pc()
        staged = []
        converted = []
        for a in args:
            if type(a).__module__.startswith("numpy") and hasattr(a, "nbytes"):
                d = jax.device_put(a)
                staged.append(d)
                converted.append(d)
            else:
                staged.append(a)
        if converted:
            _block_ready(converted)
        t1 = pc()
        with _DISPATCH_GATE:
            out = fn(*staged, **kwargs)
        t2 = pc()
        _block_ready(out)
        t3 = pc()
        _materialize(out)
        t4 = pc()
    return (
        ((t1 - t0) * 1000.0, (t2 - t1) * 1000.0,
         (t3 - t2) * 1000.0, (t4 - t3) * 1000.0),
        out,
    )


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


# One process-wide enqueue gate for sharded dispatches. JAX requires
# multi-device computations to be ENQUEUED in the same order on every
# device; two threads racing execute_sharded can invert the per-device
# queue order and deadlock the collective rendezvous (observed as reader
# threads parked in ``array._value`` while a third pjit never finishes).
# Enqueue is async and returns in microseconds — results are awaited
# OUTSIDE the gate — so concurrent queries still overlap on device; the
# gate only pins the cross-device launch order. RLock, not Lock: a step
# that re-enters Python (host fallback inside a wrapped step) must not
# self-deadlock.
_DISPATCH_GATE = threading.RLock()


def observed(name: str, fn):
    """Wrap one cached jit step with the signature-keyed sampler.

    Applied INSIDE the ``lru_cache`` factories, so each distinct compiled
    step owns one wrapper and one signature set for the life of the
    cache. Metric handles are resolved ONCE here (names are fixed per
    wrapper) so the per-dispatch cost is increments, not name lookups.
    """
    from geomesa_tpu.obs import trace as _trace

    sigs: set = set()
    lock = threading.Lock()
    reg = registry()
    calls = reg.counter(f"jax.jit.{name}.calls")
    compiles = reg.counter(f"jax.jit.{name}.compiles")
    compile_ms = reg.histogram(f"jax.jit.{name}.compile_dispatch_ms")
    dispatch_ms = reg.histogram(f"jax.jit.{name}.dispatch_ms")
    recompiles = reg.counter(f"jax.jit.{name}.recompiles")
    recompiles_all = reg.counter("jax.jit.recompiles")
    h2d_bytes = reg.counter("jax.transfer.h2d_bytes")
    d2h_bytes = reg.counter("jax.transfer.d2h_bytes")

    def wrapper(*args, **kwargs):
        key = _abstract_sig(args)
        with lock:
            is_new = key not in sigs
            if is_new:
                sigs.add(key)
            n_sigs = len(sigs)
        sp = _trace.span("jit", step=name) if _trace.active() else None
        # devprof: ONE module-global bool check on the off path (<2%
        # bound); the ContextVar read happens only while some profiled()
        # context is live anywhere in the process
        prof = _devmon.current_profile() if _devmon.PROFILING else None
        prof_detail = None
        t0 = time.perf_counter()
        try:
            if prof is not None:
                prof_detail, out = _profiled_call(fn, args, kwargs, sp)
            elif sp is not None:
                with sp:
                    with _DISPATCH_GATE:
                        out = fn(*args, **kwargs)
            else:
                with _DISPATCH_GATE:
                    out = fn(*args, **kwargs)
        except BaseException:
            # the signature only counts once the step SUCCEEDS: a device
            # error here (circuit-breaker failover) must leave the retry
            # classified as the compile it really is, not a warm dispatch
            if is_new:
                with lock:
                    sigs.discard(key)
            raise
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1000.0
        calls.inc()
        # transfer denominator: numpy args are about to cross host→device
        # (call sites that pre-convert account theirs via count_h2d —
        # dedupe by array identity so the SAME array arriving here after a
        # count_h2d on this thread is never counted twice per dispatch);
        # result bytes cross back when the caller materializes them
        pre = _consume_pending_h2d()
        h2d = sum(
            int(a.nbytes)
            for a in args
            if type(a).__module__.startswith("numpy")
            and hasattr(a, "nbytes") and id(a) not in pre
        )
        d2h = _nbytes(out)
        if h2d:
            h2d_bytes.inc(h2d)
        if d2h:
            d2h_bytes.inc(d2h)
        # roundtrip ledger (obs.ledger): one ContextVar read when no query
        # context is open; the on path charges this dispatch's span +
        # transfer bytes to the live query's ledger
        _ledger.note_dispatch(t0, t1, compiled=is_new,
                              h2d_bytes=h2d, d2h_bytes=d2h)
        if is_new:
            compiles.inc()
            compile_ms.update(dt_ms)
            if n_sigs > 1:
                # a warm step met a fresh abstract signature: the live J003
                recompiles_all.inc()
                recompiles.inc()
                _note_recompile(name)
        else:
            dispatch_ms.update(dt_ms)
        if sp is not None:
            sp.set(compile=is_new, ms=round(dt_ms, 3),
                   h2d_bytes=h2d, d2h_bytes=d2h)
        if prof is not None and prof_detail is not None:
            h2d_ms, call_ms, device_ms, d2h_ms = prof_detail
            prof.add(
                name,
                compile_ms=call_ms if is_new else 0.0,
                dispatch_ms=0.0 if is_new else call_ms,
                device_ms=device_ms, h2d_ms=h2d_ms, d2h_ms=d2h_ms,
                h2d_bytes=h2d, d2h_bytes=d2h,
            )
            if sp is not None:
                sp.set(device_ms=round(device_ms, 3),
                       h2d_ms=round(h2d_ms, 3), d2h_ms=round(d2h_ms, 3))
        return out

    wrapper.__name__ = f"observed_{name}"
    wrapper.__wrapped__ = fn
    return wrapper


def jit_report() -> dict:
    """Per-step jit census: calls, distinct-signature compiles, recompiles,
    and dispatch timing — the live J003 dashboard."""
    if GLOBAL is None:
        return {}
    snap = GLOBAL.snapshot()
    steps: dict[str, dict] = {}
    for k, v in snap.items():
        if not k.startswith("jax.jit."):
            continue
        rest = k[len("jax.jit."):]
        if "." not in rest:
            continue  # jax.jit.recompiles global counter
        step, metric = rest.rsplit(".", 1)
        if metric in ("calls", "compiles", "recompiles"):
            steps.setdefault(step, {})[metric] = v.get("count", 0)
        elif metric in ("dispatch_ms", "compile_dispatch_ms"):
            steps.setdefault(step, {})[metric] = {
                kk: vv for kk, vv in v.items() if kk != "type"
            }
    out = {"steps": steps}
    if "jax.jit.recompiles" in snap:
        out["recompiles"] = snap["jax.jit.recompiles"]["count"]
    for k in ("jax.transfer.h2d_bytes", "jax.transfer.d2h_bytes"):
        if k in snap:
            out[k.rsplit(".", 1)[1]] = snap[k]["count"]
    return out
