"""JAX compile/dispatch telemetry (the live counterpart to tpulint J003).

Two hooks:

- :func:`install` registers ``jax.monitoring`` duration listeners so every
  jit compile in the process lands in the telemetry registry as
  ``jax.compile.*`` histograms (trace time, MLIR lowering, backend
  compile). Guarded: a ``GEOMESA_TPU_NO_JAX=1`` process never imports jax
  from here, and a missing/old jax degrades to a no-op.

- :func:`observed` wraps a cached jit step (the ``cached_*_step``
  factories in :mod:`geomesa_tpu.parallel.query`) with a per-call sampler
  that keys calls by ABSTRACT SIGNATURE — the (shape, dtype) tuple jax
  itself caches on. A new signature on an already-warm step is exactly the
  recompile hazard tpulint's J003 flags statically; here it increments
  ``jax.jit.recompiles`` live, with per-step compile/dispatch timing
  histograms and host↔device transfer-byte counters. The sampler itself
  never calls into jax: it reads ``shape``/``dtype``/``nbytes`` attributes
  off whatever arguments arrive and nothing else.

Telemetry is always-on and cheap (~1-2 µs per dispatch, against device
calls that cost milliseconds); SPANS for jit calls are only emitted while
tracing is active (:mod:`geomesa_tpu.obs.trace`).
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["GLOBAL", "registry", "install", "observed", "jit_report"]

GLOBAL = None  # lazily-created MetricsRegistry (process-wide jax telemetry)
_reg_lock = threading.Lock()
_installed = False


def registry():
    """The process-wide telemetry registry (created on first use)."""
    global GLOBAL
    if GLOBAL is None:
        with _reg_lock:
            if GLOBAL is None:
                from geomesa_tpu.utils.metrics import MetricsRegistry

                GLOBAL = MetricsRegistry()
    return GLOBAL


def _on_duration(name: str, secs: float, **_kw) -> None:
    # e.g. /jax/core/compile/backend_compile_duration → jax.compile.backend_compile
    if "/compile/" not in name:
        return
    tail = name.rsplit("/", 1)[1]
    if tail.endswith("_duration"):
        tail = tail[: -len("_duration")]
    reg = registry()
    reg.histogram(f"jax.compile.{tail}_ms").update(secs * 1000.0)
    reg.counter("jax.compile.events").inc()


def install() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns True when
    listening; False when jax is gated off or unavailable."""
    global _installed
    if _installed:
        return True
    if os.environ.get("GEOMESA_TPU_NO_JAX"):
        return False
    try:
        import jax.monitoring as jm
    except Exception:  # pragma: no cover — no jax in the process
        return False
    with _reg_lock:
        if _installed:
            return True
        # one-time listener registration, not a dispatch: the lock exists
        # precisely to make this registration idempotent under races
        # tpurace: disable-next-line=R003
        jm.register_event_duration_secs_listener(_on_duration)
        _installed = True
    return True


def _abstract_sig(args: tuple) -> tuple:
    """The jit cache key proxy: (shape, dtype) per array argument, type name
    for everything else (python scalars don't retrigger compiles on value)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            out.append((tuple(shape), str(getattr(a, "dtype", ""))))
        else:
            out.append(type(a).__name__)
    return tuple(out)


def _nbytes(obj) -> int:
    """Total nbytes across an array / tuple-of-arrays result (one level of
    tuple/list nesting — the shapes our steps actually return)."""
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, (tuple, list)):
        return sum(_nbytes(x) for x in obj)
    return 0


def _np_bytes(arrays) -> int:
    """Total nbytes across the NUMPY members of ``arrays`` — THE
    host-side-array detection rule for h2d accounting (one definition;
    device-resident jax arrays never count)."""
    return sum(
        int(a.nbytes)
        for a in arrays
        if type(a).__module__.startswith("numpy") and hasattr(a, "nbytes")
    )


def count_h2d(*arrays) -> int:
    """Account host→device staging for numpy arrays about to be
    ``jnp.asarray``'d / ``device_put`` / passed to a dispatch (transfers
    the step wrapper cannot see when call sites pre-convert). Non-numpy
    args are skipped — device-resident columns must not be recounted per
    dispatch. Returns bytes counted."""
    total = _np_bytes(arrays)
    if total:
        registry().counter("jax.transfer.h2d_bytes").inc(total)
    return total


def observed(name: str, fn):
    """Wrap one cached jit step with the signature-keyed sampler.

    Applied INSIDE the ``lru_cache`` factories, so each distinct compiled
    step owns one wrapper and one signature set for the life of the
    cache. Metric handles are resolved ONCE here (names are fixed per
    wrapper) so the per-dispatch cost is increments, not name lookups.
    """
    from geomesa_tpu.obs import trace as _trace

    sigs: set = set()
    lock = threading.Lock()
    reg = registry()
    calls = reg.counter(f"jax.jit.{name}.calls")
    compiles = reg.counter(f"jax.jit.{name}.compiles")
    compile_ms = reg.histogram(f"jax.jit.{name}.compile_dispatch_ms")
    dispatch_ms = reg.histogram(f"jax.jit.{name}.dispatch_ms")
    recompiles = reg.counter(f"jax.jit.{name}.recompiles")
    recompiles_all = reg.counter("jax.jit.recompiles")
    h2d_bytes = reg.counter("jax.transfer.h2d_bytes")
    d2h_bytes = reg.counter("jax.transfer.d2h_bytes")

    def wrapper(*args, **kwargs):
        key = _abstract_sig(args)
        with lock:
            is_new = key not in sigs
            if is_new:
                sigs.add(key)
            n_sigs = len(sigs)
        sp = _trace.span("jit", step=name) if _trace.active() else None
        t0 = time.perf_counter()
        try:
            if sp is not None:
                with sp:
                    out = fn(*args, **kwargs)
            else:
                out = fn(*args, **kwargs)
        except BaseException:
            # the signature only counts once the step SUCCEEDS: a device
            # error here (circuit-breaker failover) must leave the retry
            # classified as the compile it really is, not a warm dispatch
            if is_new:
                with lock:
                    sigs.discard(key)
            raise
        dt_ms = (time.perf_counter() - t0) * 1000.0
        calls.inc()
        # transfer denominator: numpy args are about to cross host→device
        # (call sites that pre-convert account theirs via count_h2d);
        # result bytes cross back when the caller materializes them
        h2d = _np_bytes(args)
        d2h = _nbytes(out)
        if h2d:
            h2d_bytes.inc(h2d)
        if d2h:
            d2h_bytes.inc(d2h)
        if is_new:
            compiles.inc()
            compile_ms.update(dt_ms)
            if n_sigs > 1:
                # a warm step met a fresh abstract signature: the live J003
                recompiles_all.inc()
                recompiles.inc()
        else:
            dispatch_ms.update(dt_ms)
        if sp is not None:
            sp.set(compile=is_new, ms=round(dt_ms, 3),
                   h2d_bytes=h2d, d2h_bytes=d2h)
        return out

    wrapper.__name__ = f"observed_{name}"
    wrapper.__wrapped__ = fn
    return wrapper


def jit_report() -> dict:
    """Per-step jit census: calls, distinct-signature compiles, recompiles,
    and dispatch timing — the live J003 dashboard."""
    if GLOBAL is None:
        return {}
    snap = GLOBAL.snapshot()
    steps: dict[str, dict] = {}
    for k, v in snap.items():
        if not k.startswith("jax.jit."):
            continue
        rest = k[len("jax.jit."):]
        if "." not in rest:
            continue  # jax.jit.recompiles global counter
        step, metric = rest.rsplit(".", 1)
        if metric in ("calls", "compiles", "recompiles"):
            steps.setdefault(step, {})[metric] = v.get("count", 0)
        elif metric in ("dispatch_ms", "compile_dispatch_ms"):
            steps.setdefault(step, {})[metric] = {
                kk: vv for kk, vv in v.items() if kk != "type"
            }
    out = {"steps": steps}
    if "jax.jit.recompiles" in snap:
        out["recompiles"] = snap["jax.jit.recompiles"]["count"]
    for k in ("jax.transfer.h2d_bytes", "jax.transfer.d2h_bytes"):
        if k in snap:
            out[k.rsplit(".", 1)[1]] = snap[k]["count"]
    return out
