"""Z2 (points) and XZ2 (extended geometries) spatial-only indexes.

Reference: ``geomesa-index-api/.../index/z2/Z2IndexKeySpace.scala`` (row =
``[shard][8B z2][id]``) and ``XZ2IndexKeySpace.scala``. Same TPU re-design as
:mod:`geomesa_tpu.index.z3`: sort order over the columnar snapshot + row
intervals, no byte rows or shard prefixes.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.curve.sfc import Z2SFC
from geomesa_tpu.curve.xz import xz2_sfc
from geomesa_tpu.filter.bounds import Extraction
from geomesa_tpu.index.api import (
    DEFAULT_MAX_RANGES,
    FeatureIndex,
    IndexPlan,
    intervals_from_key_ranges,
    merge_intervals,
)
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType


class Z2Index(FeatureIndex):
    name = "z2"

    def __init__(self, sft: FeatureType):
        super().__init__(sft)
        if sft.index_layout == "legacy":
            from geomesa_tpu.curve.legacy import LegacyZ2SFC

            self.sfc = LegacyZ2SFC()
        else:
            self.sfc = Z2SFC()
        self.zs: np.ndarray | None = None

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        return sft.geom_is_points

    def can_serve(self, e: Extraction) -> bool:
        return True

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        col = table.geom_column()
        z = self.sfc.index(col.x, col.y)
        # 62-bit z2 fits the device key exactly and cannot reach the
        # reshard sentinel (all-ones u64)
        if sorter is not None and len(z) and int(z.max()) != 2**64 - 1:
            perm = sorter(z, None)
        else:
            from geomesa_tpu import native

            perm = native.sort_u64(z)
        self.perm = perm
        self.zs = z[perm]
        self.n = len(table)
        return perm

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        if e.disjoint or self.n == 0:
            return IndexPlan.empty()
        if e.boxes is None:
            return IndexPlan.full(self.n)
        zranges = self.sfc.ranges(e.boxes, max_ranges)
        out = intervals_from_key_ranges(self.zs, zranges)
        return IndexPlan(merge_intervals(out))


class XZ2Index(FeatureIndex):
    name = "xz2"

    def __init__(self, sft: FeatureType):
        super().__init__(sft)
        self.sfc = xz2_sfc(sft.xz_precision)
        self.codes: np.ndarray | None = None

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        return sft.geom_field is not None and not sft.geom_is_points

    def can_serve(self, e: Extraction) -> bool:
        return True

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        b = table.geom_column().bounds
        codes = self.sfc.index((b[:, 0], b[:, 1]), (b[:, 2], b[:, 3]))
        if sorter is not None and len(codes) and int(codes.max()) != 2**64 - 1:
            perm = sorter(codes, None)
        else:
            from geomesa_tpu import native

            perm = native.sort_u64(codes)
        self.perm = perm
        self.codes = codes[perm]
        self.n = len(table)
        return perm

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        if e.disjoint or self.n == 0:
            return IndexPlan.empty()
        if e.boxes is None:
            return IndexPlan.full(self.n)
        windows = [((x1, y1), (x2, y2)) for x1, y1, x2, y2 in e.boxes]
        cranges = self.sfc.ranges(windows, max_ranges)
        out = intervals_from_key_ranges(self.codes, cranges)
        return IndexPlan(merge_intervals(out))


class IdIndex(FeatureIndex):
    """Feature-id index (``geomesa-index-api/.../index/id/``): sort by fid."""

    name = "id"

    def __init__(self, sft: FeatureType):
        super().__init__(sft)
        self.fids: np.ndarray | None = None

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        return True

    def can_serve(self, e: Extraction) -> bool:
        return True

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        perm = np.argsort(table.fids, kind="stable")
        self.perm = perm
        self.fids = table.fids[perm]
        self.n = len(table)
        return perm

    def plan_fids(self, fids) -> IndexPlan:
        out = []
        for fid in fids:
            lo = int(np.searchsorted(self.fids, fid, side="left"))
            hi = int(np.searchsorted(self.fids, fid, side="right"))
            if hi > lo:
                out.append((lo, hi))
        return IndexPlan(merge_intervals(out), exact=True)

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        return IndexPlan.full(self.n)
