"""Index API: the middle seam (``GeoMesaFeatureIndex`` / ``IndexKeySpace`` role).

Reference contracts re-materialized TPU-first (SURVEY.md §1 seam 2,
``geomesa-index-api/.../api/GeoMesaFeatureIndex.scala:49``,
``IndexKeySpace.scala:23``): an index is (a) a permutation that sorts a feature
batch by its key order, and (b) a planner from extracted filter bounds to
**row intervals in that sort order**. Row intervals are this framework's
universal scan IR — the role byte ranges play in the reference
(``index/api/package.scala:276-330``) — because on a TPU the store is a set of
columnar device arrays sorted in index order, and a scan is a gather of
candidate slots, not a BatchScanner RPC.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar

import numpy as np

from geomesa_tpu.filter.bounds import Extraction
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType

DEFAULT_MAX_RANGES = 2000  # reference QueryProperties.ScanRangesTarget default


@dataclass
class IndexPlan:
    """Scan plan for one index over one snapshot: sorted-row intervals.

    ``intervals``: (R, 2) int64 ``[start, end)`` in sorted-row positions.
    ``exact``: True when interval membership alone implies a filter match for
    the *primary* predicate (no z false positives — e.g. full-domain scans);
    the full residual filter is applied downstream regardless.

    ``exec_cache``: backend-owned dispatch-payload memo. Plans live in the
    store's plan cache and repeat verbatim for repeated filters; the TPU
    backend stashes the derived per-shard split and the staged device
    payloads here (keyed by layout shape) so the cached-plan path pays
    ZERO host planning/staging per query. Entries are only valid for a
    layout with the same (rows_per_shard, kind) — the key carries both —
    and the plan cache itself is dropped on every state swap, so a stale
    payload can never pair with fresh residency. Excluded from equality.
    """

    intervals: np.ndarray
    exact: bool = False
    exec_cache: dict = field(default_factory=dict, compare=False, repr=False)

    @property
    def n_candidates(self) -> int:
        if len(self.intervals) == 0:
            return 0
        return int((self.intervals[:, 1] - self.intervals[:, 0]).sum())

    @staticmethod
    def empty() -> "IndexPlan":
        return IndexPlan(np.empty((0, 2), dtype=np.int64))

    @staticmethod
    def full(n: int) -> "IndexPlan":
        return IndexPlan(np.array([[0, n]], dtype=np.int64))


class FeatureIndex:
    """One configured index over a feature type. Subclasses define key order.

    Lifecycle: ``build(table)`` computes the sort permutation and retains the
    (host-side) sorted key arrays needed for planning; ``plan(extraction)``
    maps filter bounds to sorted-row intervals.
    """

    name: ClassVar[str] = "base"

    def __init__(self, sft: FeatureType):
        self.sft = sft
        self.perm: np.ndarray | None = None  # sorted position -> original row
        self.n = 0

    # -- capability tests (StrategyDecider inputs) ---------------------------
    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        raise NotImplementedError

    def can_serve(self, e: Extraction) -> bool:
        raise NotImplementedError

    # -- build ---------------------------------------------------------------
    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        """Compute and retain sort state; returns the permutation.

        ``sorter``: optional device sort — ``sorter(route_key_u64,
        tiebreak_i32_or_None) -> perm`` (the mesh sample-sort from
        :func:`geomesa_tpu.store.device_ingest.device_sort_perm`). Indexes
        whose keys map onto it use it in place of the host sort; others
        ignore it. Implementations must fall back to the host sort when the
        composite key cannot be expressed (e.g. out-of-range time bins).
        """
        raise NotImplementedError

    # -- plan ----------------------------------------------------------------
    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        raise NotImplementedError


def intervals_from_key_ranges(
    sorted_keys: np.ndarray, ranges: np.ndarray, offset: int = 0
) -> list[tuple[int, int]]:
    """Map inclusive key ranges to [start, end) positions via binary search.

    ``sorted_keys`` must be ascending; ``ranges`` is (R, 2) inclusive in key
    space. This is the host-side analog of the tablet server seeking each
    range: O(R log N), vectorized.
    """
    if len(ranges) == 0 or len(sorted_keys) == 0:
        return []
    starts = np.searchsorted(sorted_keys, ranges[:, 0], side="left") + offset
    ends = np.searchsorted(sorted_keys, ranges[:, 1], side="right") + offset
    keep = ends > starts
    return list(zip(starts[keep].tolist(), ends[keep].tolist()))


def merge_intervals(intervals: list[tuple[int, int]]) -> np.ndarray:
    """Sort + coalesce overlapping/adjacent [start, end) intervals."""
    if not intervals:
        return np.empty((0, 2), dtype=np.int64)
    intervals.sort()
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return np.asarray(out, dtype=np.int64)


def gather_indices(intervals: np.ndarray, pad_to: int | None = None):
    """Expand [start, end) intervals into a flat array of row positions.

    The host-side prelude to a device gather: candidate slots are contiguous
    spans of the sorted store. Returns (idx int64, count) where idx is padded
    with ``idx[0]`` (a harmless duplicate; padding slots are masked out by the
    kernel via ``count``).
    """
    if len(intervals) == 0:
        idx = np.zeros(pad_to or 0, dtype=np.int64)
        return idx, 0
    lens = intervals[:, 1] - intervals[:, 0]
    total = int(lens.sum())
    # vectorized concatenation of aranges
    idx = np.repeat(intervals[:, 0], lens) + (
        np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
    )
    if pad_to is not None:
        if pad_to < total:
            raise ValueError(f"pad_to {pad_to} < candidate count {total}")
        pad = np.full(pad_to - total, idx[0] if total else 0, dtype=np.int64)
        idx = np.concatenate([idx, pad])
    return idx.astype(np.int64), total


def pad_bucket(n: int, minimum: int = 1024) -> int:
    """Round up to a power of two — bounds the jit compile cache."""
    b = minimum
    while b < n:
        b <<= 1
    return b
