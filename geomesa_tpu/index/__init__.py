"""geomesa_tpu subpackage."""
