"""Attribute index: per-attribute value order with a Z3 secondary tier.

Reference: ``geomesa-index-api/.../index/attribute/AttributeIndex.scala:61`` —
rows keyed ``[shard][attr idx][lexicoded value][tiered z3/date][id]`` with
values lexicoded so byte order = natural order (``AttributeIndexKey.scala``).
TPU re-design: **no lexicoding needed** — the index sorts the columnar
snapshot by (value, time-bin, z3) directly (numpy handles natural ordering),
value predicates map to row intervals via binary search over the sorted value
array, and the Z3 tier is realized by planning z-ranges *within* each
equal-value run (the ``GeoMesaFeatureIndex.getQueryStrategy`` tiering of
``GeoMesaFeatureIndex.scala:249-339``). Null values sort to the end and are
excluded from every planned range.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.curve.binned_time import BinnedTime
from geomesa_tpu.curve.sfc import z3_sfc
from geomesa_tpu.filter.bounds import Extraction
from geomesa_tpu.index.api import (
    DEFAULT_MAX_RANGES,
    FeatureIndex,
    IndexPlan,
    intervals_from_key_ranges,
    merge_intervals,
)
from geomesa_tpu.index.z3 import time_windows
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import AttributeType, FeatureType


class AttributeIndex(FeatureIndex):
    """One instance per indexed attribute; named ``attr:<name>``."""

    name = "attr"

    def __init__(self, sft: FeatureType, attribute: str):
        super().__init__(sft)
        self.attribute = attribute
        self.name = f"attr:{attribute}"
        self.tiered = sft.geom_is_points and sft.dtg_field is not None
        if self.tiered:
            self.period = sft.z3_interval
            self.binned = BinnedTime(self.period)
            self.sfc = z3_sfc(self.period)
        self.values: np.ndarray | None = None  # sorted values (valid rows first)
        self.n_valid = 0
        self.bins: np.ndarray | None = None
        self.zs: np.ndarray | None = None

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:  # pragma: no cover - factory
        return True

    def can_serve(self, e: Extraction) -> bool:
        return e.attr_bounded(self.attribute)

    @staticmethod
    def indexed_attributes(sft: FeatureType) -> list[str]:
        return [
            a.name
            for a in sft.attributes
            if a.indexed and not a.type.is_geometry
        ]

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        # attribute keys (strings etc.) don't map onto the u64 device sort
        col = table.columns[self.attribute]
        valid = col.is_valid()
        vals = col.values
        # sortable surrogate: None -> pushed to end via the valid flag
        if self.tiered:
            tcol = table.geom_column()
            bins, offs = self.binned.to_bin_and_offset(table.dtg_millis())
            z = self.sfc.index(tcol.x, tcol.y, offs)
            order = stable_lexsort([z, bins, _sort_surrogate(vals, valid), ~valid])
            self.bins = bins[order]
            self.zs = z[order]
        else:
            order = stable_lexsort([_sort_surrogate(vals, valid), ~valid])
        self.perm = order
        self.values = vals[order]
        self.n = len(table)
        self.n_valid = int(valid.sum())
        return order

    # -- planning ------------------------------------------------------------
    def _value_span(self, lo, hi, lo_inc, hi_inc) -> tuple[int, int]:
        """Row span [start, end) of values within the interval (valid rows)."""
        vals = self.values[: self.n_valid]
        if lo is None:
            start = 0
        else:
            start = int(np.searchsorted(vals, lo, side="left" if lo_inc else "right"))
        if hi is None:
            end = self.n_valid
        else:
            end = int(np.searchsorted(vals, hi, side="right" if hi_inc else "left"))
        return start, max(end, start)

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        bounds = e.attributes.get(self.attribute)
        if e.disjoint or self.n == 0:
            return IndexPlan.empty()
        if bounds is None:
            # full scan INCLUDING null-attribute rows (they sort to the end
            # and the residual filter decides their fate)
            return IndexPlan.full(self.n)
        out: list[tuple[int, int]] = []
        for lo, hi, li, ri in bounds:
            start, end = self._value_span(lo, hi, li, ri)
            if end <= start:
                continue
            # Z3 tier: for equality runs with temporal bounds, narrow by
            # (bin, z) within the run — the tiered-key-space trick.
            if (
                self.tiered
                and lo is not None
                and lo == hi
                and (e.intervals is not None or e.boxes is not None)
                and end - start > 64
            ):
                out.extend(self._tiered(start, end, e, max_ranges))
            else:
                out.append((start, end))
        return IndexPlan(merge_intervals(out))

    def _tiered(self, start: int, end: int, e: Extraction, max_ranges: int):
        from geomesa_tpu.index.z3 import WORLD

        boxes = e.boxes if e.boxes is not None else [WORLD]
        run_bins = self.bins[start:end]
        bin_values = np.unique(run_bins)
        windows = time_windows(self.binned, bin_values, e.intervals)
        if not windows:
            return []
        budget = max(1, max_ranges // max(1, len(windows)))
        out = []
        for b, w_lo, w_hi in windows:
            blo = start + int(np.searchsorted(run_bins, b, side="left"))
            bhi = start + int(np.searchsorted(run_bins, b, side="right"))
            if bhi <= blo:
                continue
            zr = self.sfc.ranges(boxes, (float(w_lo), float(w_hi)), budget)
            out.extend(
                intervals_from_key_ranges(self.zs[blo:bhi], zr, offset=blo)
            )
        return out


def stable_lexsort(keys: list[np.ndarray]) -> np.ndarray:
    """np.lexsort replacement that supports object (string) key arrays:
    chained stable argsorts, least-significant key first."""
    n = len(keys[0])
    order = np.arange(n)
    for k in keys:
        order = order[np.argsort(k[order], kind="stable")]
    return order


def _sort_surrogate(vals: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """A sortable key array: invalid slots get the first valid value (their
    position is controlled by the ``~valid`` primary key in lexsort)."""
    if valid.all():
        return vals
    out = vals.copy()
    if valid.any():
        fill = vals[valid][0]
    else:
        fill = 0
    out[~valid] = fill
    return out
