"""Z3 (points + time) and XZ3 (extended geometries + time) indexes.

Reference: ``geomesa-index-api/.../index/z3/Z3Index.scala:19`` with key layout
``[shard][2B time-bin][8B z3][id]`` and ``Z3IndexKeySpace.scala`` (toIndexKey:64,
getIndexValues:98, getRanges:162) / ``XZ3IndexKeySpace.scala``. TPU re-design:
no byte rows — the sort order is ``(time-bin, z3)`` over the columnar snapshot,
bins are tracked as contiguous sorted-row spans (they double as the coarse
partition axis), and planning splits the range budget across bins exactly like
``Z3IndexKeySpace.scala:165-177``. Hash shards (``ShardStrategy.scala``) are
unnecessary on a device mesh — sharding happens by slicing the sorted store
(SURVEY.md §2.20 P1/P2).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.curve.binned_time import BinnedTime
from geomesa_tpu.curve.sfc import z3_sfc
from geomesa_tpu.curve.xz import xz3_sfc
from geomesa_tpu.filter.bounds import Extraction
from geomesa_tpu.index.api import (
    DEFAULT_MAX_RANGES,
    FeatureIndex,
    IndexPlan,
    intervals_from_key_ranges,
    merge_intervals,
)
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType

WORLD = (-180.0, -90.0, 180.0, 90.0)


def _lexsort_bin_key(bins: np.ndarray, key: np.ndarray, sorter) -> np.ndarray:
    """Sort rows by (time-bin, curve key), on device when a sorter is given.

    The 79-bit composite (16-bit bin, 63-bit key) rides the 64-bit device
    sample sort as ``route = bin<<48 | key>>15`` with the dropped low 15
    bits as the tiebreak column — exact because ``route`` is a monotone
    prefix of the wide key. Bins outside u16 (or a sorter failure the
    caller didn't catch) fall back to the host lexsort.
    """
    if (
        sorter is not None
        and len(bins)
        and 0 <= int(bins.min())
        and int(bins.max()) < (1 << 16)
        # the route packs key>>15 into 48 bits: keys >= 2^63 (e.g. XZ3 at
        # extreme precision) would overflow into the bin field — host sort
        and int(key.max()) < (1 << 63)
    ):
        route = (bins.astype(np.uint64) << np.uint64(48)) | (
            key.astype(np.uint64) >> np.uint64(15)
        )
        # all-ones route == the reshard padding sentinel; that row would be
        # silently dropped from the permutation — host sort handles it
        if int(route.max()) != 2**64 - 1:
            tie = (key.astype(np.uint64) & np.uint64(0x7FFF)).astype(np.int32)
            return sorter(route, tie)
    from geomesa_tpu import native

    return native.lexsort_bin_z(bins, key)


def time_windows(
    binned: BinnedTime, bin_values: np.ndarray, intervals
) -> list[tuple[int, int, int]]:
    """Expand temporal bounds into per-bin (bin, off_lo, off_hi) windows,
    clipped to bins actually present in the data (shared by Z3 and XZ3 —
    the per-bin budget split of ``Z3IndexKeySpace.scala:165-177``)."""
    if len(bin_values) == 0:
        return []
    max_off = int(binned.max_offset)
    if intervals is None:
        return [(int(b), 0, max_off) for b in bin_values]
    out = []
    for lo_ms, hi_ms in intervals:
        lo_ms = max(int(lo_ms), 0)
        # clamp to the last millisecond of the last bin present in the data
        hi_ms = min(
            int(hi_ms),
            int(binned.bin_start_millis(np.array([int(bin_values[-1]) + 1]))[0]) - 1,
        )
        if hi_ms < lo_ms:
            continue
        (blo,), (olo,) = binned.to_bin_and_offset(np.array([lo_ms]))
        (bhi,), (ohi,) = binned.to_bin_and_offset(np.array([hi_ms]))
        sel = (bin_values >= blo) & (bin_values <= bhi)
        for b in bin_values[sel]:
            w_lo = int(olo) if b == blo else 0
            w_hi = int(ohi) if b == bhi else max_off
            out.append((int(b), w_lo, w_hi))
    return out


class Z3Index(FeatureIndex):
    name = "z3"

    def __init__(self, sft: FeatureType):
        super().__init__(sft)
        self.period = sft.z3_interval
        self.binned = BinnedTime(self.period)
        if sft.index_layout == "legacy":
            from geomesa_tpu.curve.legacy import legacy_z3_sfc

            self.sfc = legacy_z3_sfc(self.period)
        else:
            self.sfc = z3_sfc(self.period)
        # build products
        self.bins: np.ndarray | None = None  # sorted (n,) int32
        self.zs: np.ndarray | None = None  # sorted (n,) uint64
        self.offsets: np.ndarray | None = None  # sorted (n,) int64 offsets
        self.bin_values: np.ndarray | None = None  # unique bins present
        self.bin_starts: np.ndarray | None = None  # row span starts per bin

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        return sft.geom_is_points and sft.dtg_field is not None

    def can_serve(self, e: Extraction) -> bool:
        return True  # full-domain scan degrades gracefully

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        col = table.geom_column()
        t_ms = table.dtg_millis()
        bins, offs = self.binned.to_bin_and_offset(t_ms)
        z = self.sfc.index(col.x, col.y, offs)
        perm = _lexsort_bin_key(bins, z, sorter)
        self.perm = perm
        self.bins = bins[perm]
        self.offsets = offs[perm]
        self.zs = z[perm]
        self.n = len(table)
        self.bin_values, self.bin_starts = np.unique(self.bins, return_index=True)
        return perm

    def merge_build(self, table: FeatureTable, prev: "Z3Index", n_prev: int) -> np.ndarray:
        """LSM-style incremental build: ``table`` = [prev's rows | delta].

        The main tier is already (bin, z)-sorted in ``prev``; only the delta
        is sorted (small), then linearly merged (``native.merge_bin_z``) —
        O(n) instead of a full re-sort, the compaction pattern of SURVEY.md
        §2.11. Result is bit-identical to :meth:`build` on the whole table
        (stable ties: main rows precede delta rows, as in the full sort).
        """
        from geomesa_tpu import native

        n = len(table)
        if prev.n != n_prev or n_prev == 0 or prev.bins is None:
            return self.build(table)
        col = table.geom_column()
        sl = slice(n_prev, n)
        d_bins, d_offs = self.binned.to_bin_and_offset(table.dtg_millis()[sl])
        d_z = self.sfc.index(col.x[sl], col.y[sl], d_offs)
        d_perm = native.lexsort_bin_z(d_bins, d_z)
        d_bins_s = d_bins[d_perm]
        d_z_s = d_z[d_perm]
        merged = native.merge_bin_z(prev.bins, prev.zs, d_bins_s, d_z_s)
        in_main = merged < n_prev
        perm = np.where(
            in_main,
            prev.perm[np.minimum(merged, n_prev - 1)],
            n_prev + d_perm[np.maximum(merged - n_prev, 0)],
        )
        self.perm = perm
        self.bins = np.where(in_main, prev.bins[np.minimum(merged, n_prev - 1)],
                             d_bins_s[np.maximum(merged - n_prev, 0)])
        self.zs = np.where(in_main, prev.zs[np.minimum(merged, n_prev - 1)],
                           d_z_s[np.maximum(merged - n_prev, 0)])
        self.offsets = np.where(
            in_main, prev.offsets[np.minimum(merged, n_prev - 1)],
            d_offs[d_perm][np.maximum(merged - n_prev, 0)],
        )
        self.n = n
        self.bin_values, self.bin_starts = np.unique(self.bins, return_index=True)
        return perm

    # -- planning ------------------------------------------------------------
    def _bin_span(self, b: int) -> tuple[int, int]:
        i = np.searchsorted(self.bin_values, b)
        if i == len(self.bin_values) or self.bin_values[i] != b:
            return (0, 0)
        start = int(self.bin_starts[i])
        end = int(self.bin_starts[i + 1]) if i + 1 < len(self.bin_starts) else self.n
        return (start, end)

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        if e.disjoint or self.n == 0:
            return IndexPlan.empty()
        boxes = e.boxes if e.boxes is not None else [WORLD]
        windows = time_windows(self.binned, self.bin_values, e.intervals)
        if not windows:
            return IndexPlan.empty()
        budget = max(1, max_ranges // max(1, len(windows)))
        out: list[tuple[int, int]] = []
        for b, w_lo, w_hi in windows:
            start, end = self._bin_span(b)
            if end <= start:
                continue
            zranges = self.sfc.ranges(boxes, (float(w_lo), float(w_hi)), budget)
            out.extend(
                intervals_from_key_ranges(self.zs[start:end], zranges, offset=start)
            )
        return IndexPlan(merge_intervals(out))


class XZ3Index(FeatureIndex):
    """XZ3: bbox-of-geometry + time instant, for non-point default geometries."""

    name = "xz3"

    def __init__(self, sft: FeatureType):
        super().__init__(sft)
        self.period = sft.z3_interval
        self.binned = BinnedTime(self.period)
        self.sfc = xz3_sfc(self.period, sft.xz_precision)
        self.bins: np.ndarray | None = None
        self.codes: np.ndarray | None = None
        self.bin_values: np.ndarray | None = None
        self.bin_starts: np.ndarray | None = None

    @classmethod
    def supports(cls, sft: FeatureType) -> bool:
        return (
            sft.geom_field is not None
            and not sft.geom_is_points
            and sft.dtg_field is not None
        )

    def can_serve(self, e: Extraction) -> bool:
        return True

    def build(self, table: FeatureTable, sorter=None) -> np.ndarray:
        col = table.geom_column()
        b = col.bounds  # (n, 4)
        t_ms = table.dtg_millis()
        bins, offs = self.binned.to_bin_and_offset(t_ms)
        o = offs.astype(np.float64)
        codes = self.sfc.index(
            (b[:, 0], b[:, 1], o), (b[:, 2], b[:, 3], o)
        )
        perm = _lexsort_bin_key(bins, codes, sorter)
        self.perm = perm
        self.bins = bins[perm]
        self.codes = codes[perm]
        self.n = len(table)
        self.bin_values, self.bin_starts = np.unique(self.bins, return_index=True)
        return perm

    def merge_build(self, table: FeatureTable, prev: "XZ3Index", n_prev: int) -> np.ndarray:
        """Linear LSM merge of a sorted delta into the sorted main tier
        (same contract as :meth:`Z3Index.merge_build`)."""
        from geomesa_tpu import native

        n = len(table)
        if prev.n != n_prev or n_prev == 0 or prev.bins is None:
            return self.build(table)
        col = table.geom_column()
        b = col.bounds[n_prev:n]
        d_bins, d_offs = self.binned.to_bin_and_offset(table.dtg_millis()[n_prev:n])
        o = d_offs.astype(np.float64)
        d_codes = self.sfc.index((b[:, 0], b[:, 1], o), (b[:, 2], b[:, 3], o))
        d_perm = native.lexsort_bin_z(d_bins, d_codes)
        d_bins_s = d_bins[d_perm]
        d_codes_s = d_codes[d_perm]
        merged = native.merge_bin_z(prev.bins, prev.codes, d_bins_s, d_codes_s)
        in_main = merged < n_prev
        main_i = np.minimum(merged, n_prev - 1)
        delta_i = np.maximum(merged - n_prev, 0)
        self.perm = np.where(in_main, prev.perm[main_i], n_prev + d_perm[delta_i])
        self.bins = np.where(in_main, prev.bins[main_i], d_bins_s[delta_i])
        self.codes = np.where(in_main, prev.codes[main_i], d_codes_s[delta_i])
        self.n = n
        self.bin_values, self.bin_starts = np.unique(self.bins, return_index=True)
        return self.perm

    def _bin_span(self, b: int) -> tuple[int, int]:
        i = np.searchsorted(self.bin_values, b)
        if i == len(self.bin_values) or self.bin_values[i] != b:
            return (0, 0)
        start = int(self.bin_starts[i])
        end = int(self.bin_starts[i + 1]) if i + 1 < len(self.bin_starts) else self.n
        return (start, end)

    def plan(self, e: Extraction, max_ranges: int = DEFAULT_MAX_RANGES) -> IndexPlan:
        if e.disjoint or self.n == 0:
            return IndexPlan.empty()
        boxes = e.boxes if e.boxes is not None else [WORLD]
        windows = time_windows(self.binned, self.bin_values, e.intervals)
        if not windows:
            return IndexPlan.empty()
        budget = max(1, max_ranges // max(1, len(windows)))
        out: list[tuple[int, int]] = []
        for b, w_lo, w_hi in windows:
            start, end = self._bin_span(b)
            if end <= start:
                continue
            wins = [
                ((x1, y1, float(w_lo)), (x2, y2, float(w_hi)))
                for x1, y1, x2, y2 in boxes
            ]
            cranges = self.sfc.ranges(wins, budget)
            out.extend(
                intervals_from_key_ranges(self.codes[start:end], cranges, offset=start)
            )
        return IndexPlan(merge_intervals(out))
