"""Self-contained Leaflet map HTML for notebooks.

Role parity: ``geomesa-jupyter`` (325 LoC — SURVEY.md §2.19): render query
results as an interactive Leaflet map in a notebook. Output is a single HTML
document (Leaflet from its public CDN; data embedded as GeoJSON), usable via
``IPython.display.HTML`` or saved to a file.
"""

from __future__ import annotations

import json

import numpy as np

__all__ = ["map_html", "density_layer", "show"]

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"/>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>#map{{height:{height}px;}}</style></head>
<body><div id="map"></div><script>
var map = L.map('map');
L.tileLayer('https://tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
            {{maxZoom: 19, attribution: '&copy; OpenStreetMap'}}).addTo(map);
var layers = {layers};
var group = L.featureGroup();
layers.forEach(function (spec) {{
  if (spec.kind === 'geojson') {{
    L.geoJSON(spec.data, {{
      style: spec.style,
      pointToLayer: function (f, latlng) {{
        return L.circleMarker(latlng, spec.style);
      }},
      onEachFeature: function (f, l) {{
        if (f.properties) {{
          l.bindPopup(Object.entries(f.properties)
            .map(function (kv) {{ return kv[0] + ': ' + kv[1]; }}).join('<br/>'));
        }}
      }}
    }}).addTo(group);
  }} else if (spec.kind === 'density') {{
    spec.cells.forEach(function (c) {{
      L.rectangle([[c[1], c[0]], [c[3], c[2]]],
                  {{stroke: false, fillColor: spec.color,
                    fillOpacity: c[4]}}).addTo(group);
    }});
  }}
}});
group.addTo(map);
var b = group.getBounds();
if (b.isValid()) {{ map.fitBounds(b.pad(0.1)); }} else {{ map.setView([0,0],2); }}
</script></body></html>"""


def density_layer(grid: np.ndarray, bbox, color: str = "#d53e4f", max_cells: int = 4000) -> dict:
    """Density grid → rectangle layer spec (cell opacity ∝ weight)."""
    xmin, ymin, xmax, ymax = bbox
    h, w = grid.shape
    gy, gx = np.nonzero(grid)
    weights = grid[gy, gx]
    if len(gx) > max_cells:  # keep the heaviest cells
        top = np.argsort(weights)[-max_cells:]
        gy, gx, weights = gy[top], gx[top], weights[top]
    peak = float(weights.max()) if len(weights) else 1.0
    cw = (xmax - xmin) / w
    ch = (ymax - ymin) / h
    cells = [
        [
            round(xmin + x * cw, 6),
            round(ymin + y * ch, 6),
            round(xmin + (x + 1) * cw, 6),
            round(ymin + (y + 1) * ch, 6),
            round(0.15 + 0.85 * float(v) / peak, 3),
        ]
        for x, y, v in zip(gx, gy, weights)
    ]
    return {"kind": "density", "cells": cells, "color": color}


def map_html(*layers, height: int = 500) -> str:
    """Layers → standalone HTML. Each layer may be a FeatureTable, a GeoJSON
    FeatureCollection dict, a (table_or_fc, style_dict) tuple, or a
    :func:`density_layer` spec."""
    specs = []
    for layer in layers:
        style = {"radius": 4, "color": "#3288bd", "weight": 1, "fillOpacity": 0.7}
        if isinstance(layer, tuple):
            layer, style = layer[0], {**style, **layer[1]}
        if isinstance(layer, dict) and layer.get("kind") == "density":
            specs.append(layer)
            continue
        if isinstance(layer, dict):
            fc = layer
        else:  # FeatureTable
            from geomesa_tpu.geometry.geojson import table_to_feature_collection

            fc = table_to_feature_collection(layer)
        specs.append({"kind": "geojson", "data": fc, "style": style})
    # escape script-context breakers: feature properties/fids are user data,
    # and '</script>' inside json.dumps would terminate the <script> block
    # (stored XSS when served over HTTP). < is valid JSON for '<'.
    payload = (
        json.dumps(specs)
        .replace("<", "\\u003c")
        .replace(">", "\\u003e")
        .replace("&", "\\u0026")
    )
    return _PAGE.format(height=height, layers=payload)


def show(*layers, height: int = 500):
    """IPython display object (falls back to the HTML string)."""
    html = map_html(*layers, height=height)
    try:
        from IPython.display import HTML

        return HTML(html)
    except ImportError:
        return html
