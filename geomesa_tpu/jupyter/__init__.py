"""Notebook map display helpers."""

from geomesa_tpu.jupyter.leaflet import density_layer, map_html, show

__all__ = ["map_html", "density_layer", "show"]
