"""EXIF GPS extraction: geo-locate JPEG blobs from their own metadata.

Role parity: the reference blobstore's file handlers
(``geomesa-blobstore`` EXIF/GDAL handler modules, SURVEY.md §2.8) derive a
blob's footprint from the file itself. This is a dependency-free parser of
just enough JPEG/TIFF structure to read the EXIF GPS IFD: APP1 segment →
TIFF header (either endianness) → IFD0 → GPS IFD → latitude/longitude
rationals (+ optional timestamp), returning a Point and epoch millis.
"""

from __future__ import annotations

import struct

from geomesa_tpu.geometry.types import Point

__all__ = ["exif_gps", "put_jpeg"]

_TYPE_SIZES = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 7: 1, 9: 4, 10: 8}


def _find_app1(data: bytes) -> bytes | None:
    """The Exif APP1 payload (after the 'Exif\\0\\0' marker), or None."""
    if data[:2] != b"\xff\xd8":  # SOI
        return None
    pos = 2
    while pos + 4 <= len(data):
        if data[pos] != 0xFF:
            return None
        # JPEG B.1.1.2: any number of 0xFF fill bytes may precede a marker
        while pos + 4 <= len(data) and data[pos + 1] == 0xFF:
            pos += 1
        if pos + 4 > len(data):
            return None
        marker = data[pos + 1]
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            pos += 2
            continue
        (seg_len,) = struct.unpack_from(">H", data, pos + 2)
        if marker == 0xE1 and data[pos + 4 : pos + 10] == b"Exif\x00\x00":
            return data[pos + 10 : pos + 2 + seg_len]
        if marker == 0xDA:  # start of scan: no more metadata segments
            return None
        pos += 2 + seg_len
    return None


def _read_ifd(tiff: bytes, offset: int, endian: str) -> dict[int, tuple]:
    """tag → (type, count, value_or_offset_bytes) for one IFD."""
    out: dict[int, tuple] = {}
    if offset + 2 > len(tiff):
        return out
    (n,) = struct.unpack_from(endian + "H", tiff, offset)
    pos = offset + 2
    for _ in range(n):
        if pos + 12 > len(tiff):
            break
        tag, typ, count = struct.unpack_from(endian + "HHI", tiff, pos)
        out[tag] = (typ, count, tiff[pos + 8 : pos + 12])
        pos += 12
    return out

def _value_offset(entry: tuple, endian: str) -> int:
    return struct.unpack(endian + "I", entry[2])[0]


def _rationals(tiff: bytes, entry: tuple, endian: str) -> list[float]:
    typ, count, raw = entry
    if typ not in (5, 10):
        return []
    off = _value_offset(entry, endian)
    out = []
    for i in range(count):
        base = off + 8 * i
        if base + 8 > len(tiff):
            return []
        num, den = struct.unpack_from(endian + ("II" if typ == 5 else "ii"), tiff, base)
        out.append(num / den if den else 0.0)
    return out


def _ascii(tiff: bytes, entry: tuple, endian: str) -> str:
    typ, count, raw = entry
    if count <= 4:
        data = raw[:count]
    else:
        off = _value_offset(entry, endian)
        data = tiff[off : off + count]
    return data.split(b"\x00")[0].decode("ascii", "replace")


def exif_gps(data: bytes):
    """JPEG bytes → (Point(lon, lat), epoch_ms | None), or None if no GPS.

    Timestamp combines GPSDateStamp (tag 0x1D) + GPSTimeStamp (0x07) when
    both are present (UTC per the EXIF spec).
    """
    tiff = _find_app1(data)
    if tiff is None or len(tiff) < 8:
        return None
    if tiff[:2] == b"II":
        endian = "<"
    elif tiff[:2] == b"MM":
        endian = ">"
    else:
        return None
    (ifd0_off,) = struct.unpack_from(endian + "I", tiff, 4)
    ifd0 = _read_ifd(tiff, ifd0_off, endian)
    gps_entry = ifd0.get(0x8825)  # GPS IFD pointer
    if gps_entry is None:
        return None
    gps = _read_ifd(tiff, _value_offset(gps_entry, endian), endian)
    try:
        lat_ref = _ascii(tiff, gps[0x01], endian)
        lat_dms = _rationals(tiff, gps[0x02], endian)
        lon_ref = _ascii(tiff, gps[0x03], endian)
        lon_dms = _rationals(tiff, gps[0x04], endian)
    except KeyError:
        return None
    if len(lat_dms) < 3 or len(lon_dms) < 3:
        return None
    lat = lat_dms[0] + lat_dms[1] / 60 + lat_dms[2] / 3600
    lon = lon_dms[0] + lon_dms[1] / 60 + lon_dms[2] / 3600
    if lat_ref.upper().startswith("S"):
        lat = -lat
    if lon_ref.upper().startswith("W"):
        lon = -lon
    if abs(lon) > 180 or abs(lat) > 90:
        return None

    ts_ms = None
    if 0x1D in gps and 0x07 in gps:
        try:
            date = _ascii(tiff, gps[0x1D], endian)  # "YYYY:MM:DD"
            hms = _rationals(tiff, gps[0x07], endian)
            y, m, d = (int(p) for p in date.split(":"))
            import datetime

            ts_ms = int(
                datetime.datetime(
                    y, m, d, int(hms[0]), int(hms[1]), int(hms[2]),
                    tzinfo=datetime.timezone.utc,
                ).timestamp() * 1000
            )
        except (ValueError, IndexError):
            ts_ms = None
    return Point(lon, lat), ts_ms


def put_jpeg(blobstore, data: bytes | str, filename: str | None = None,
             dtg_ms: int | None = None) -> str:
    """Store a JPEG, footprint derived from its EXIF GPS tags (handler role).

    Raises ValueError when the image carries no GPS metadata; ``dtg_ms``
    overrides (or supplies, when EXIF lacks a GPS timestamp) the date.
    """
    from geomesa_tpu.blob.store import normalize_payload

    data, filename = normalize_payload(data, filename)
    got = exif_gps(data)
    if got is None:
        raise ValueError("no EXIF GPS metadata; pass geometry to put() instead")
    point, exif_ms = got
    when = dtg_ms if dtg_ms is not None else exif_ms
    if when is None:
        raise ValueError("no timestamp: EXIF lacks GPSDate/TimeStamp; pass dtg_ms")
    return blobstore.put(data, point, when, filename=filename)
