"""Spatially-indexed blob storage."""

from geomesa_tpu.blob.store import BlobStore

__all__ = ["BlobStore"]
