"""Spatially-indexed blob storage.

Role parity: ``geomesa-blobstore`` (1,396 LoC — SURVEY.md §2.8): arbitrary
files/bytes stored under generated ids, with a spatial+temporal metadata
feature per blob so blobs are discoverable by the normal query language
("all imagery intersecting this bbox last week"). The reference extracts
geometry from the file itself (GDAL/EXIF handlers) or takes it explicitly;
here handlers are pluggable callables and the default expects explicit
geometry.
"""

from __future__ import annotations

import uuid
from pathlib import Path

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.sft import parse_spec

_SPEC = "filename:String,dtg:Date,*geom:Geometry"
_TYPE = "geomesa_blobs"


def normalize_payload(data, filename: str | None) -> tuple[bytes, str]:
    """(bytes-or-path, filename?) → (bytes, filename) — shared by put() and
    the file handlers (blob/exif.py)."""
    if isinstance(data, (str, Path)):
        p = Path(data)
        filename = filename or p.name
        data = p.read_bytes()
    if filename is None:
        raise ValueError("filename required when passing raw bytes")
    return data, filename


class BlobStore:
    """Blobs (bytes or files) + a queryable spatial metadata feature each.

    ``directory``: blob payloads on disk (one file per id); omitted → bytes
    held in memory. Metadata rides a normal datastore schema, so every query
    capability (CQL, bbox/time, processes) applies to blob discovery.
    """

    def __init__(self, store=None, directory: str | None = None):
        if store is None:
            from geomesa_tpu.store.datastore import DataStore

            store = DataStore(backend="tpu")
        self.store = store
        if _TYPE not in store.list_schemas():
            store.create_schema(parse_spec(_TYPE, _SPEC))
        self.directory = Path(directory) if directory else None
        if self.directory:
            self.directory.mkdir(parents=True, exist_ok=True)
        self._blobs: dict[str, bytes] = {}

    # -- write ---------------------------------------------------------------
    def put(
        self,
        data: bytes | str,
        geometry,
        dtg_ms: int,
        filename: str | None = None,
    ) -> str:
        """Store bytes (or a file path) with its footprint; returns the id."""
        data, filename = normalize_payload(data, filename)
        blob_id = uuid.uuid4().hex
        self.store.write(
            _TYPE,
            [{"filename": filename, "dtg": dtg_ms, "geom": geometry}],
            fids=[blob_id],
        )
        if self.directory:
            (self.directory / blob_id).write_bytes(data)
        else:
            self._blobs[blob_id] = data
        return blob_id

    # -- read ----------------------------------------------------------------
    def get(self, blob_id: str) -> tuple[bytes, dict]:
        """(payload, metadata) for one id."""
        from geomesa_tpu.filter import ast

        r = self.store.query(_TYPE, Query(filter=ast.FidIn([blob_id])))
        if r.count == 0 or not self._has_payload(blob_id):
            raise KeyError(f"no such blob: {blob_id!r}")
        meta = r.table.record(0)
        try:
            if self.directory:
                payload = (self.directory / blob_id).read_bytes()
            else:
                payload = self._blobs[blob_id]
        except (FileNotFoundError, KeyError):
            raise KeyError(f"no such blob: {blob_id!r}") from None
        return payload, meta

    def _has_payload(self, blob_id: str) -> bool:
        # deletion tombstone IS payload absence (ids are fresh uuid4s, never
        # re-put), so deletes made through any BlobStore instance over the
        # same directory are seen by all of them
        if self.directory:
            return (self.directory / blob_id).exists()
        return blob_id in self._blobs

    def query_ids(self, cql=None) -> list[tuple[str, str]]:
        """[(blob_id, filename)] matching a CQL/AST filter over the metadata."""
        r = self.store.query(_TYPE, Query(filter=cql))
        names = r.table.columns["filename"].values
        return [
            (str(f), str(n))
            for f, n in zip(r.table.fids, names)
            if self._has_payload(str(f))
        ]

    def delete(self, blob_id: str) -> None:
        # metadata rows are append-only in the main store; deletion removes
        # the payload, and get/query_ids filter on payload absence
        if self.directory:
            (self.directory / blob_id).unlink(missing_ok=True)
        else:
            self._blobs.pop(blob_id, None)
