"""GeoTIFF blob handler: georeferenced-raster ingestion without GDAL.

Role parity: the reference's blobstore registers GDAL-backed handlers that
extract a footprint from georeferenced files (``geomesa-blobstore``,
SURVEY.md §2.8 — VERDICT r3 missing #5). A GeoTIFF is a TIFF whose
georeferencing lives in plain TIFF tags, so a ~100-line tag reader covers
the footprint-extraction role: ModelPixelScale (33550) + ModelTiepoint
(33922) give the affine grid, and the GeoKeyDirectory (34735) names the
CRS, which the CRS kit (:mod:`geomesa_tpu.utils.crs`) transforms onto the
lon/lat datum — UTM-projected GeoTIFFs land correctly. ``put_geotiff``
stores the blob with its footprint feature and can additionally load the
pixels into the raster store as a queryable chip.
"""

from __future__ import annotations

import struct

import numpy as np

from geomesa_tpu.geometry.types import Polygon

__all__ = ["geotiff_bounds", "put_geotiff"]

_TAG_WIDTH = 256
_TAG_HEIGHT = 257
_TAG_PIXEL_SCALE = 33550
_TAG_TIEPOINT = 33922
_TAG_TRANSFORM = 34264
_TAG_GEOKEYS = 34735

# bytes per TIFF field type (we read SHORT/LONG/DOUBLE)
_TYPE_SIZES = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 11: 4, 12: 8}
_TYPE_FMT = {3: "H", 4: "I", 11: "f", 12: "d"}


def _read_ifd(data: bytes, offset: int, endian: str) -> dict[int, tuple]:
    (n,) = struct.unpack_from(endian + "H", data, offset)
    out = {}
    for i in range(n):
        tag, typ, count, val = struct.unpack_from(
            endian + "HHI4s", data, offset + 2 + i * 12
        )
        out[tag] = (typ, count, val)
    return out


def _values(data: bytes, entry: tuple, endian: str) -> list:
    typ, count, raw = entry
    size = _TYPE_SIZES.get(typ)
    fmt = _TYPE_FMT.get(typ)
    if size is None or fmt is None:
        raise ValueError(f"unsupported TIFF field type {typ}")
    total = size * count
    if total <= 4:
        buf = raw[:total]
    else:
        (off,) = struct.unpack(endian + "I", raw)
        buf = data[off:off + total]
    return list(struct.unpack(endian + fmt * count, buf))


def _geokey_epsg(data: bytes, ifd: dict, endian: str) -> int | None:
    """GeoKeyDirectory → the EPSG code of the raster CRS (projected key
    3072 wins over geographic key 2048)."""
    entry = ifd.get(_TAG_GEOKEYS)
    if entry is None:
        return None
    keys = _values(data, entry, endian)
    epsg = None
    for i in range(4, len(keys) - 3, 4):
        key_id, loc, _count, value = keys[i:i + 4]
        if loc != 0:
            continue  # value lives in an aux tag; only inline shorts matter
        if key_id == 3072 and 1024 <= value < 32768:
            return int(value)
        if key_id == 2048 and 1024 <= value < 32768:
            epsg = int(value)
    return epsg


def geotiff_bounds(data: bytes) -> tuple[tuple, str]:
    """GeoTIFF bytes → ((xmin, ymin, xmax, ymax) in lon/lat, source CRS).

    Raises ValueError for TIFFs without georeferencing tags or with a CRS
    the kit cannot transform."""
    if len(data) < 8:
        raise ValueError("not a TIFF")
    if data[:2] == b"II":
        endian = "<"
    elif data[:2] == b"MM":
        endian = ">"
    else:
        raise ValueError("not a TIFF (bad byte-order mark)")
    try:
        (magic,) = struct.unpack_from(endian + "H", data, 2)
        if magic != 42:
            raise ValueError("not a TIFF (bad magic)")
        (ifd_off,) = struct.unpack_from(endian + "I", data, 4)
        ifd = _read_ifd(data, ifd_off, endian)
        try:
            width = int(_values(data, ifd[_TAG_WIDTH], endian)[0])
            height = int(_values(data, ifd[_TAG_HEIGHT], endian)[0])
        except KeyError:
            raise ValueError("TIFF lacks image dimensions") from None
    except struct.error as e:
        # truncated/corrupt files must surface as the documented ValueError,
        # not a struct internals error
        raise ValueError(f"corrupt TIFF: {e}") from None

    try:
        if _TAG_TIEPOINT in ifd and _TAG_PIXEL_SCALE in ifd:
            tp = _values(data, ifd[_TAG_TIEPOINT], endian)
            sx, sy = _values(data, ifd[_TAG_PIXEL_SCALE], endian)[:2]
            # tiepoint: raster (i, j, k) ↔ model (x, y, z); y decreases
            # down rows
            i, j, _k, x, y = tp[0], tp[1], tp[2], tp[3], tp[4]
            x0 = x - i * sx
            y_top = y + j * sy
            corners_x = np.array([x0, x0 + width * sx])
            corners_y = np.array([y_top - height * sy, y_top])
        elif _TAG_TRANSFORM in ifd:
            m = _values(data, ifd[_TAG_TRANSFORM], endian)
            ii = np.array([0.0, width, 0.0, width])
            jj = np.array([0.0, 0.0, height, height])
            xs = m[0] * ii + m[1] * jj + m[3]
            ys = m[4] * ii + m[5] * jj + m[7]
            corners_x = np.array([xs.min(), xs.max()])
            corners_y = np.array([ys.min(), ys.max()])
        else:
            raise ValueError("TIFF carries no georeferencing tags")

        epsg = _geokey_epsg(data, ifd, endian) or 4326
    except (struct.error, IndexError) as e:
        raise ValueError(f"corrupt TIFF: {e}") from None
    crs = f"EPSG:{epsg}"
    if epsg != 4326:
        from geomesa_tpu.utils.crs import transform_coords

        # transform all four corners: projected axes do not stay axis-
        # aligned in lon/lat
        cx = np.array([corners_x[0], corners_x[1], corners_x[0], corners_x[1]])
        cy = np.array([corners_y[0], corners_y[0], corners_y[1], corners_y[1]])
        lon, lat = transform_coords(cx, cy, crs, "EPSG:4326")
        return (
            (float(lon.min()), float(lat.min()),
             float(lon.max()), float(lat.max())),
            crs,
        )
    return (
        (float(corners_x.min()), float(corners_y.min()),
         float(corners_x.max()), float(corners_y.max())),
        crs,
    )


def put_geotiff(blobstore, data, filename: str | None = None,
                dtg_ms: int = 0, raster_store=None) -> str:
    """Store a GeoTIFF with its georeferenced footprint (handler role);
    optionally also load its pixels into ``raster_store`` as a chip.

    Returns the blob id. Raises ValueError for non-georeferenced TIFFs."""
    from geomesa_tpu.blob.store import normalize_payload

    data, filename = normalize_payload(data, filename)
    (xmin, ymin, xmax, ymax), _crs = geotiff_bounds(data)
    footprint = Polygon([
        [xmin, ymin], [xmax, ymin], [xmax, ymax], [xmin, ymax],
    ])
    blob_id = blobstore.put(data, footprint, dtg_ms, filename=filename)
    if raster_store is not None:
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(data))
        chip = np.asarray(img.convert("F"), dtype=np.float64)
        raster_store.put(chip, (xmin, ymin, xmax, ymax))
    return blob_id
